"""Active scalar-field dispatch for HOST-side synthesis arithmetic.

The CS layer (witness resolvers, constant reduction, gate coefficient
normalization) historically hardwired Goldilocks (`field/gl.py`). With the
BabyBear backend driving the full prover (ISSUE 20), every host scalar op
the synthesis path performs must reduce mod the ACTIVE field's prime or
the witness itself is wrong — an fma chain computed mod 2^64-2^32+1 is
not a valid BabyBear trace.

`scalar_field()` returns a namespace with the handful of host ops the CS
layer uses (`P`, `add`, `sub`, `mul`, `neg`, `inv`, `pow_`). For
Goldilocks it returns `field/gl.py` ITSELF, so the default path is
byte-identical to the pre-ISSUE-20 behavior; for BabyBear it returns a
thin shim over `field/babybear.py`'s `*_s` host scalars. Resolution reads
``BOOJUM_TPU_FIELD`` at CALL time (like `field/spec.py`), so tests can
flip the backend per-case.
"""

from __future__ import annotations

from . import gl
from .spec import active_field


class _BabyBearScalars:
    """Host scalar ops shim matching field/gl.py's names."""

    from . import babybear as _bb

    P = _bb.P
    add = staticmethod(_bb.add_s)
    sub = staticmethod(_bb.sub_s)
    mul = staticmethod(_bb.mul_s)
    neg = staticmethod(_bb.neg_s)
    inv = staticmethod(_bb.inv_s)
    pow_ = staticmethod(_bb.pow_s)


def scalar_field():
    """The active field's host scalar namespace (gl module or BB shim)."""
    if active_field() == "babybear":
        return _BabyBearScalars
    return gl


def field_p() -> int:
    """The active field's prime (synthesis-time constant reduction)."""
    return scalar_field().P
