"""Fused prefix-product scans and Montgomery batch inversion as Pallas TPU
kernels over u32 limb planes.

The XLA path (`goldilocks.prefix_product` / `batch_inverse`) is a
Hillis-Steele log-doubling scan: log2(n) full passes over HBM per scan, and a
batch inversion is two scans plus combines (~70 array passes at n = 2^16).
This module is the TPU-kernel counterpart of the reference's serial
Montgomery trick (`/root/reference/src/cs/implementations/utils.rs:405`
batch_inverse / its parallel chunked form): a classic block-scan —

  pass A (left->right grid): per (64, 128) VMEM tile, an in-tile
    Hillis-Steele (7 lane-roll steps + 6 sublane-roll steps on row totals),
    then multiply by a running carry kept in VMEM scratch across grid steps
    -> the inclusive prefix products P in 2 HBM passes, plus a per-tile
    carry-out row (the prefix up to the tile's end, lane-replicated) used by
    pass B for tile-boundary values.
  middle: ONE Fermat inversion of the per-row totals (tiny, XLA).
  pass B (right->left grid): inverses out[i] = P[i-1] * S_excl[i] * T^-1,
    where the exclusive suffix products S come from an in-tile reverse scan
    plus a right-to-left carry; P[i-1] at a tile's first element is the left
    neighbor's carry-out row from pass A.

Mosaic layout note: every cross-tile value is kept as a real (1, 128)
lane-replicated row (scratch or pass-A output) — Mosaic cannot broadcast a
(1, 1) scalar to both sublanes and lanes in one op, so scalars never appear;
replication happens by lane-broadcasting an (R, 1) column (legal) and
slicing one row.

All products are exact mod-p field ops, so results are BIT-IDENTICAL to the
XLA path regardless of association order. Scan element order is the flat
row-major order of the (rows, 128) tile view — i.e. the array's natural last
axis order, matching the XLA scans.

An extension-field (GF(p^2)) inclusive scan kernel is included for the
copy-permutation grand product z (prover/stages.py:_ext_prefix_prod), whose
XLA form pays 3x the passes (each ext mul is 3 base muls).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gl
from . import limbs
from ..utils.pallas_util import imap32

_LANE = 128
_ROWS = 64  # tile rows: 64x128 = 8192 elements per grid step
_MIN_N = 1 << 13  # below this the XLA scans win (kernel launch overhead)


def size_fits(n: int) -> bool:
    return n >= _MIN_N and n % (_ROWS * _LANE) == 0


# ---------------------------------------------------------------------------
# In-tile scan helpers (operate on limb pairs of shape (R, 128))
# ---------------------------------------------------------------------------


def _iota(shape, axis):
    return jax.lax.broadcasted_iota(jnp.int32, shape, axis)


def _roll(x, k, axis):
    return (jnp.roll(x[0], k, axis=axis), jnp.roll(x[1], k, axis=axis))


def _where(mask, a, b):
    return (jnp.where(mask, a[0], b[0]), jnp.where(mask, a[1], b[1]))


def _ones_like(x):
    return (jnp.ones_like(x[0]), jnp.zeros_like(x[1]))


def _rep_row(col_pair, idx: int, R: int):
    """(R, 1) limb-pair column -> its row `idx` replicated as (1, 128).

    Lane-broadcast of a column is legal in Mosaic; slicing then avoids the
    unsupported (1,1)->both-axes broadcast."""
    full = (
        jnp.broadcast_to(col_pair[0], (R, _LANE)),
        jnp.broadcast_to(col_pair[1], (R, _LANE)),
    )
    return (full[0][idx : idx + 1], full[1][idx : idx + 1])


def _tile_incl_scan(x, mul):
    """Inclusive product scan of an (R, 128) tile in flat row-major order.

    Returns (scanned, row_totals_incl) where row_totals_incl is (R, 1)."""
    R = x[0].shape[0]
    lane = _iota(x[0].shape, 1)
    for k in (1, 2, 4, 8, 16, 32, 64):
        x = _where(lane >= k, mul(x, _roll(x, k, 1)), x)
    t = (x[0][:, _LANE - 1 :], x[1][:, _LANE - 1 :])
    row = _iota(t[0].shape, 0)
    k = 1
    while k < R:
        t = _where(row >= k, mul(t, _roll(t, k, 0)), t)
        k *= 2
    excl = _roll(t, 1, 0)
    excl = _where(row == 0, _ones_like(excl), excl)
    return mul(x, excl), t


def _tile_rev_incl_scan(x, mul):
    """Reverse (suffix) inclusive product scan of an (R, 128) tile.

    Returns (scanned, row_suffix_totals_incl (R, 1))."""
    R = x[0].shape[0]
    lane = _iota(x[0].shape, 1)
    for k in (1, 2, 4, 8, 16, 32, 64):
        x = _where(lane < _LANE - k, mul(x, _roll(x, -k, 1)), x)
    t = (x[0][:, :1], x[1][:, :1])
    row = _iota(t[0].shape, 0)
    k = 1
    while k < R:
        t = _where(row < R - k, mul(t, _roll(t, -k, 0)), t)
        k *= 2
    excl = _roll(t, -1, 0)
    excl = _where(row == R - 1, _ones_like(excl), excl)
    return mul(x, excl), t


# ---------------------------------------------------------------------------
# Pass A: inclusive prefix products (+ per-tile carry-out rows)
# ---------------------------------------------------------------------------


def _prefix_kernel(xl, xh, ol, oh, col, coh, clo, chi):
    nb = pl.program_id(1)

    @pl.when(nb == 0)
    def _():
        clo[:] = jnp.ones((1, _LANE), jnp.uint32)
        chi[:] = jnp.zeros((1, _LANE), jnp.uint32)

    x = (xl[0, 0], xh[0, 0])
    R = x[0].shape[0]
    scanned, totals = _tile_incl_scan(x, limbs.mul)
    carry = (clo[:], chi[:])
    scanned = limbs.mul(scanned, carry)
    tile_total = _rep_row(totals, R - 1, R)
    new_carry = limbs.mul(carry, tile_total)
    clo[:] = new_carry[0]
    chi[:] = new_carry[1]
    ol[0, 0] = scanned[0]
    oh[0, 0] = scanned[1]
    col[0, 0] = new_carry[0]
    coh[0, 0] = new_carry[1]


# ---------------------------------------------------------------------------
# Pass B: inverses from prefixes + reverse scan
# ---------------------------------------------------------------------------


def _inv_kernel(NB: int, al, ah, pl_, ph_, bl, bh, tl, th,
                ol, oh, clo, chi):
    nb_rev = pl.program_id(1)  # 0 = rightmost tile

    @pl.when(nb_rev == 0)
    def _():
        clo[:] = jnp.ones((1, _LANE), jnp.uint32)
        chi[:] = jnp.zeros((1, _LANE), jnp.uint32)

    a = (al[0, 0], ah[0, 0])
    P = (pl_[0, 0], ph_[0, 0])
    R = a[0].shape[0]
    lane = _iota(a[0].shape, 1)
    row = _iota(a[0].shape, 0)

    # exclusive suffix products within the tile, then fold in the right carry
    s_incl, s_tot = _tile_rev_incl_scan(a, limbs.mul)
    nxt = _roll(s_incl, -1, 1)  # lane l <- lane l+1
    col_next = _roll((s_incl[0][:, :1], s_incl[1][:, :1]), -1, 0)
    nxt = _where(
        lane == _LANE - 1,
        (
            jnp.broadcast_to(col_next[0], a[0].shape),
            jnp.broadcast_to(col_next[1], a[1].shape),
        ),
        nxt,
    )
    s_excl = _where((row == R - 1) & (lane == _LANE - 1), _ones_like(nxt), nxt)
    carry = (clo[:], chi[:])
    s_excl = limbs.mul(s_excl, carry)

    # shifted prefix P[i-1]: lane shift, row boundary, tile boundary
    prv = _roll(P, 1, 1)
    col_prev = _roll((P[0][:, -1:], P[1][:, -1:]), 1, 0)
    prv = _where(
        lane == 0,
        (
            jnp.broadcast_to(col_prev[0], a[0].shape),
            jnp.broadcast_to(col_prev[1], a[1].shape),
        ),
        prv,
    )
    first = (row == 0) & (lane == 0)
    is_first_tile = nb_rev == NB - 1
    # left neighbor's pass-A carry-out row: the prefix up to this tile's
    # start, lane-replicated real data (bl/bh read the nb-1 tile, clamped)
    pp_row = (bl[0, 0], bh[0, 0])  # (1, 128)
    boundary = _where(
        is_first_tile,
        _ones_like(prv),
        (
            jnp.broadcast_to(pp_row[0], a[0].shape),
            jnp.broadcast_to(pp_row[1], a[1].shape),
        ),
    )
    prv = _where(first, boundary, prv)

    tinv = (tl[0], th[0])  # (1, 128) replicated total inverse
    out = limbs.mul(limbs.mul(prv, s_excl), tinv)
    ol[0, 0] = out[0]
    oh[0, 0] = out[1]

    new_carry = limbs.mul(carry, _rep_row(s_tot, 0, R))
    clo[:] = new_carry[0]
    chi[:] = new_carry[1]


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------

_CP = pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)


@partial(jax.jit, static_argnums=(1,))
def _prefix_planes(planes, interpret: bool):
    lo, hi = planes
    B, NB, R, _ = lo.shape
    spec = pl.BlockSpec(
        (1, 1, R, _LANE),
        imap32(lambda b, nb: (b, nb, 0, 0)),
        memory_space=pltpu.VMEM,
    )
    cspec = pl.BlockSpec(
        (1, 1, 1, _LANE),
        imap32(lambda b, nb: (b, nb, 0, 0)),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct(lo.shape, jnp.uint32)
    carry_shape = jax.ShapeDtypeStruct((B, NB, 1, _LANE), jnp.uint32)
    return pl.pallas_call(
        _prefix_kernel,
        grid=(B, NB),
        out_shape=[out_shape, out_shape, carry_shape, carry_shape],
        in_specs=[spec, spec],
        out_specs=[spec, spec, cspec, cspec],
        scratch_shapes=[
            pltpu.VMEM((1, _LANE), jnp.uint32),
            pltpu.VMEM((1, _LANE), jnp.uint32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else _CP,
    )(lo, hi)


@partial(jax.jit, static_argnums=(3,))
def _inverse_planes(a_planes, p4, tinv_planes, interpret: bool):
    alo, ahi = a_planes
    plo, phi, blo, bhi = p4
    tlo, thi = tinv_planes
    B, NB, R, _ = alo.shape

    def rev(b, nb):
        return (b, NB - 1 - nb, 0, 0)

    def rev_prev(b, nb):
        # left-neighbor tile of the one at rev(); clamps at 0 (masked in
        # kernel via the first-tile predicate)
        return (b, jnp.maximum(NB - 1 - nb - 1, 0), 0, 0)

    spec = pl.BlockSpec(
        (1, 1, R, _LANE), imap32(rev), memory_space=pltpu.VMEM
    )
    bspec = pl.BlockSpec(
        (1, 1, 1, _LANE), imap32(rev_prev), memory_space=pltpu.VMEM
    )
    tspec = pl.BlockSpec(
        (1, 1, _LANE),
        imap32(lambda b, nb: (b, 0, 0)),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct(alo.shape, jnp.uint32)
    return pl.pallas_call(
        partial(_inv_kernel, NB),
        grid=(B, NB),
        out_shape=[out_shape, out_shape],
        in_specs=[spec, spec, spec, spec, bspec, bspec, tspec, tspec],
        out_specs=[spec, spec],
        scratch_shapes=[
            pltpu.VMEM((1, _LANE), jnp.uint32),
            pltpu.VMEM((1, _LANE), jnp.uint32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else _CP,
    )(alo, ahi, plo, phi, blo, bhi, tlo, thi)


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------


def _to_planes(a: jax.Array):
    lead = a.shape[:-1]
    n = a.shape[-1]
    flat = a.reshape(-1, n // (_ROWS * _LANE), _ROWS, _LANE)
    return limbs.split(flat), lead, n


def prefix_product(a: jax.Array, interpret: bool = False) -> jax.Array:
    """Inclusive modular prefix product along the last axis (u64 in/out)."""
    planes, lead, n = _to_planes(a)
    out = _prefix_planes(planes, interpret)
    return limbs.join((out[0], out[1])).reshape(lead + (n,))


def batch_inverse(a: jax.Array, interpret: bool = False) -> jax.Array:
    """Montgomery batch inversion along the last axis (u64 in/out)."""
    from . import goldilocks as gf

    planes, lead, n = _to_planes(a)
    plo, phi, blo, bhi = _prefix_planes(planes, interpret)
    totals = limbs.join((blo[:, -1, 0, 0], bhi[:, -1, 0, 0]))  # (B,)
    tinv = gf.inv(totals)
    tinv_rep = jnp.broadcast_to(
        tinv[:, None, None], totals.shape + (1, _LANE)
    )
    tinv_planes = limbs.split(tinv_rep)
    out = _inverse_planes(planes, (plo, phi, blo, bhi), tinv_planes, interpret)
    return limbs.join(out).reshape(lead + (n,))


# ---------------------------------------------------------------------------
# Extension-field inclusive scan (for the grand-product z)
# ---------------------------------------------------------------------------


def _ext_prefix_kernel(x0l, x0h, x1l, x1h, o0l, o0h, o1l, o1h,
                       c0l, c0h, c1l, c1h):
    nb = pl.program_id(1)

    @pl.when(nb == 0)
    def _():
        c0l[:] = jnp.ones((1, _LANE), jnp.uint32)
        c0h[:] = jnp.zeros((1, _LANE), jnp.uint32)
        c1l[:] = jnp.zeros((1, _LANE), jnp.uint32)
        c1h[:] = jnp.zeros((1, _LANE), jnp.uint32)

    def emul(a, b):
        return limbs.ext_mul(a, b)

    def eroll(x, k, axis):
        return (_roll(x[0], k, axis), _roll(x[1], k, axis))

    def ewhere(m, a, b):
        return (_where(m, a[0], b[0]), _where(m, a[1], b[1]))

    x = ((x0l[0, 0], x0h[0, 0]), (x1l[0, 0], x1h[0, 0]))
    R = x[0][0].shape[0]
    lane = _iota(x[0][0].shape, 1)
    for k in (1, 2, 4, 8, 16, 32, 64):
        x = ewhere(lane >= k, emul(x, eroll(x, k, 1)), x)
    t = (
        (x[0][0][:, -1:], x[0][1][:, -1:]),
        (x[1][0][:, -1:], x[1][1][:, -1:]),
    )
    row = _iota(t[0][0].shape, 0)
    k = 1
    while k < R:
        t = ewhere(row >= k, emul(t, eroll(t, k, 0)), t)
        k *= 2
    excl = eroll(t, 1, 0)
    eone = (
        _ones_like(excl[0]),
        (jnp.zeros_like(excl[1][0]), jnp.zeros_like(excl[1][1])),
    )
    excl = ewhere(row == 0, eone, excl)
    x = emul(x, excl)
    carry = ((c0l[:], c0h[:]), (c1l[:], c1h[:]))
    x = emul(x, carry)

    tile_total = (_rep_row(t[0], R - 1, R), _rep_row(t[1], R - 1, R))
    new_carry = emul(carry, tile_total)
    c0l[:] = new_carry[0][0]
    c0h[:] = new_carry[0][1]
    c1l[:] = new_carry[1][0]
    c1h[:] = new_carry[1][1]
    o0l[0, 0] = x[0][0]
    o0h[0, 0] = x[0][1]
    o1l[0, 0] = x[1][0]
    o1h[0, 0] = x[1][1]


@partial(jax.jit, static_argnums=(1,))
def _ext_prefix_planes(planes, interpret: bool):
    p0l, p0h, p1l, p1h = planes
    B, NB, R, _ = p0l.shape
    spec = pl.BlockSpec(
        (1, 1, R, _LANE),
        imap32(lambda b, nb: (b, nb, 0, 0)),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct(p0l.shape, jnp.uint32)
    scr = [pltpu.VMEM((1, _LANE), jnp.uint32)] * 4
    return pl.pallas_call(
        _ext_prefix_kernel,
        grid=(B, NB),
        out_shape=[out_shape] * 4,
        in_specs=[spec] * 4,
        out_specs=[spec] * 4,
        scratch_shapes=scr,
        interpret=interpret,
        compiler_params=None if interpret else _CP,
    )(p0l, p0h, p1l, p1h)


def ext_prefix_product(a, interpret: bool = False):
    """Inclusive ext prefix product along the last axis; a = (c0, c1) u64."""
    c0, c1 = a
    lead = c0.shape[:-1]
    n = c0.shape[-1]
    shape = (-1, n // (_ROWS * _LANE), _ROWS, _LANE)
    p0 = limbs.split(c0.reshape(shape))
    p1 = limbs.split(c1.reshape(shape))
    o0l, o0h, o1l, o1h = _ext_prefix_planes(
        (p0[0], p0[1], p1[0], p1[1]), interpret
    )
    return (
        limbs.join((o0l, o0h)).reshape(lead + (n,)),
        limbs.join((o1l, o1h)).reshape(lead + (n,)),
    )
