"""Quadratic extension GF(p^2) = GF(p)[x] / (x^2 - 7).

Mirrors the reference `GoldilocksExt2` (non-residue 7,
`/root/reference/src/field/goldilocks/extension.rs`, generic ops
`src/field/traits/field.rs:326`). Device-side elements are pairs (c0, c1) of
uint64 arrays; host-side scalars are `(int, int)` tuples (functions suffixed
`_s`). All Fiat–Shamir challenges drawn after witness commitment live here.
"""

import jax
import jax.numpy as jnp

from . import goldilocks as gf
from . import gl

NON_RESIDUE = 7


# ---------------------------------------------------------------------------
# Device (jnp array pair) ops
# ---------------------------------------------------------------------------


def add(a, b):
    return (gf.add(a[0], b[0]), gf.add(a[1], b[1]))


def sub(a, b):
    return (gf.sub(a[0], b[0]), gf.sub(a[1], b[1]))


def neg(a):
    return (gf.neg(a[0]), gf.neg(a[1]))


def mul(a, b):
    # (a0 + a1 x)(b0 + b1 x) = a0 b0 + 7 a1 b1 + (a0 b1 + a1 b0) x
    v0 = gf.mul(a[0], b[0])
    v1 = gf.mul(a[1], b[1])
    c0 = gf.add(v0, gf.mul_small(v1, NON_RESIDUE))
    c1 = gf.add(gf.mul(a[0], b[1]), gf.mul(a[1], b[0]))
    return (c0, c1)


def mul_by_base(a, b):
    """Multiply extension element a by base-field array b."""
    return (gf.mul(a[0], b), gf.mul(a[1], b))


def sqr(a):
    return mul(a, a)


def scalar_to_arrays(s, like=None):
    """Lift a host scalar ext element (int, int) to a pair of 0-d arrays."""
    return (jnp.uint64(s[0]), jnp.uint64(s[1]))


def zeros(shape):
    return (jnp.zeros(shape, jnp.uint64), jnp.zeros(shape, jnp.uint64))


def inv(a):
    # 1/(c0 + c1 x) = (c0 - c1 x) / (c0^2 - 7 c1^2)
    d = gf.sub(gf.sqr(a[0]), gf.mul_small(gf.sqr(a[1]), NON_RESIDUE))
    dinv = gf.inv(d)
    return (gf.mul(a[0], dinv), gf.neg(gf.mul(a[1], dinv)))


@jax.jit
def batch_inverse(a):
    d = gf.sub(gf.sqr(a[0]), gf.mul_small(gf.sqr(a[1]), NON_RESIDUE))
    dinv = gf.batch_inverse(d)
    return (gf.mul(a[0], dinv), gf.neg(gf.mul(a[1], dinv)))


def pow_const(a, e: int):
    result = None
    base = a
    e = int(e)
    while e:
        if e & 1:
            result = base if result is None else mul(result, base)
        e >>= 1
        if e:
            base = sqr(base)
    if result is None:
        return (jnp.ones_like(a[0]), jnp.zeros_like(a[1]))
    return result


# ---------------------------------------------------------------------------
# Host scalar ((int, int) tuple) ops
# ---------------------------------------------------------------------------

ZERO_S = (0, 0)
ONE_S = (1, 0)


def add_s(a, b):
    return (gl.add(a[0], b[0]), gl.add(a[1], b[1]))


def sub_s(a, b):
    return (gl.sub(a[0], b[0]), gl.sub(a[1], b[1]))


def neg_s(a):
    return (gl.neg(a[0]), gl.neg(a[1]))


def mul_s(a, b):
    v0 = gl.mul(a[0], b[0])
    v1 = gl.mul(a[1], b[1])
    c0 = gl.add(v0, gl.mul(v1, NON_RESIDUE))
    c1 = gl.add(gl.mul(a[0], b[1]), gl.mul(a[1], b[0]))
    return (c0, c1)


def mul_by_base_s(a, b: int):
    return (gl.mul(a[0], b), gl.mul(a[1], b))


def sqr_s(a):
    return mul_s(a, a)


def inv_s(a):
    d = gl.sub(gl.sqr(a[0]), gl.mul(gl.sqr(a[1]), NON_RESIDUE))
    dinv = gl.inv(d)
    return (gl.mul(a[0], dinv), gl.neg(gl.mul(a[1], dinv)))


def div_s(a, b):
    return mul_s(a, inv_s(b))


def pow_s(a, e: int):
    result = ONE_S
    base = a
    e = int(e)
    while e:
        if e & 1:
            result = mul_s(result, base)
        e >>= 1
        base = sqr_s(base)
    return result


def from_base_s(v: int):
    return (v, 0)


def powers_s(base, count: int):
    out = [ONE_S] * count
    for i in range(1, count):
        out[i] = mul_s(out[i - 1], base)
    return out
