"""Host-side scalar Goldilocks arithmetic over python ints.

Used by synthesis-time code paths that are inherently sequential and tiny
(transcript, verifier, witness closures, twiddle precomputation) — the
counterpart of the reference's scalar `GoldilocksField` impl
(`/root/reference/src/field/goldilocks/mod.rs:290`). Device-scale math lives in
`goldilocks.py`.
"""

# the protocol-defining constants live on the FieldSpec record
# (field/spec.py, ISSUE 19) — re-exported here so every historical
# `gl.P` call site keeps reading the same values from one source
from .spec import GOLDILOCKS as _SPEC

P = _SPEC.p
EPSILON = 0xFFFFFFFF
MULTIPLICATIVE_GENERATOR = _SPEC.multiplicative_generator
RADIX_2_SUBGROUP_GENERATOR = _SPEC.radix2_subgroup_generator
TWO_ADICITY = _SPEC.two_adicity


def add(a: int, b: int) -> int:
    s = a + b
    return s - P if s >= P else s


def sub(a: int, b: int) -> int:
    d = a - b
    return d + P if d < 0 else d


def neg(a: int) -> int:
    return 0 if a == 0 else P - a


def mul(a: int, b: int) -> int:
    return (a * b) % P


def sqr(a: int) -> int:
    return (a * a) % P


def pow_(a: int, e: int) -> int:
    return pow(a, e, P)


def inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("inverse of zero in GF(p)")
    return pow(a, P - 2, P)


def exp_power_of_2(a: int, k: int) -> int:
    for _ in range(k):
        a = sqr(a)
    return a


def omega(log_n: int) -> int:
    """Primitive 2^log_n-th root of unity (two-adic tower)."""
    assert log_n <= TWO_ADICITY
    return exp_power_of_2(RADIX_2_SUBGROUP_GENERATOR, TWO_ADICITY - log_n)


def powers(base: int, count: int) -> list:
    out = [1] * count
    for i in range(1, count):
        out[i] = mul(out[i - 1], base)
    return out


def mul_np(a, b):
    """Vectorized canonical Goldilocks multiply on uint64 numpy arrays.

    Schoolbook 32-bit split to the 128-bit product, then the standard
    2^64 = eps (= 2^32 - 1), 2^96 = -1 reduction — all in wrapping u64
    numpy ops (same identity chain as the device kernel in
    goldilocks.py). Used for host twiddle/power-table construction where
    per-element python ints are too slow and device round-trips cost a
    remote compile each."""
    import numpy as np

    with np.errstate(over="ignore"):
        u64 = np.uint64
        M32 = u64(0xFFFFFFFF)
        a = np.asarray(a, dtype=np.uint64)
        b = np.asarray(b, dtype=np.uint64)
        a_lo, a_hi = a & M32, a >> u64(32)
        b_lo, b_hi = b & M32, b >> u64(32)
        ll = a_lo * b_lo
        lh = a_lo * b_hi
        hl = a_hi * b_lo
        hh = a_hi * b_hi
        mid = lh + hl  # may wrap: 65-bit sum
        mid_carry = (mid < lh).astype(np.uint64)
        lo128 = ll + (mid << u64(32))
        lo_carry = (lo128 < ll).astype(np.uint64)
        hi128 = hh + (mid >> u64(32)) + (mid_carry << u64(32)) + lo_carry
        # reduce: x = lo128 + hi128*2^64, hi128 = hi_hi*2^32 + hi_lo
        #   2^64 = eps, 2^96 = -1  =>  x = lo128 + hi_lo*eps - hi_hi
        hi_lo = hi128 & M32
        hi_hi = hi128 >> u64(32)
        t0 = lo128 - hi_hi
        borrow = (lo128 < hi_hi).astype(np.uint64)
        t0 -= borrow * u64(EPSILON)  # the wrapped excess 2^64 = eps
        t1 = hi_lo * u64(EPSILON)  # exact: < 2^64
        res = t0 + t1
        carry = (res < t1).astype(np.uint64)
        res += carry * u64(EPSILON)
        # canonicalize
        ge = res >= u64(P)
        res = np.where(ge, res - u64(P), res)
        return res


def powers_np(base: int, count: int):
    """[1, b, ..., b^(count-1)] as a uint64 numpy array (log-doubling)."""
    import numpy as np

    out = np.ones(count, dtype=np.uint64)
    if count <= 1:
        return out
    cur = 1
    while cur < count:
        step = np.uint64(pow_(base, cur))
        nxt = min(cur, count - cur)
        out[cur : cur + nxt] = mul_np(out[:nxt], step)
        cur += nxt
    return out


def from_u64_with_reduction(x: int) -> int:
    return x % P


def as_bits_le(x: int, num_bits: int = 64) -> list:
    return [(x >> i) & 1 for i in range(num_bits)]
