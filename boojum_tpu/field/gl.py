"""Host-side scalar Goldilocks arithmetic over python ints.

Used by synthesis-time code paths that are inherently sequential and tiny
(transcript, verifier, witness closures, twiddle precomputation) — the
counterpart of the reference's scalar `GoldilocksField` impl
(`/root/reference/src/field/goldilocks/mod.rs:290`). Device-scale math lives in
`goldilocks.py`.
"""

P = 0xFFFFFFFF00000001
EPSILON = 0xFFFFFFFF
MULTIPLICATIVE_GENERATOR = 7
RADIX_2_SUBGROUP_GENERATOR = 0x185629DCDA58878C
TWO_ADICITY = 32


def add(a: int, b: int) -> int:
    s = a + b
    return s - P if s >= P else s


def sub(a: int, b: int) -> int:
    d = a - b
    return d + P if d < 0 else d


def neg(a: int) -> int:
    return 0 if a == 0 else P - a


def mul(a: int, b: int) -> int:
    return (a * b) % P


def sqr(a: int) -> int:
    return (a * a) % P


def pow_(a: int, e: int) -> int:
    return pow(a, e, P)


def inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("inverse of zero in GF(p)")
    return pow(a, P - 2, P)


def exp_power_of_2(a: int, k: int) -> int:
    for _ in range(k):
        a = sqr(a)
    return a


def omega(log_n: int) -> int:
    """Primitive 2^log_n-th root of unity (two-adic tower)."""
    assert log_n <= TWO_ADICITY
    return exp_power_of_2(RADIX_2_SUBGROUP_GENERATOR, TWO_ADICITY - log_n)


def powers(base: int, count: int) -> list:
    out = [1] * count
    for i in range(1, count):
        out[i] = mul(out[i - 1], base)
    return out


def from_u64_with_reduction(x: int) -> int:
    return x % P


def as_bits_le(x: int, num_bits: int = 64) -> list:
    return [(x >> i) & 1 for i in range(num_bits)]
