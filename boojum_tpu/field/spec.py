"""FieldSpec: the one place a field's protocol constants live (ISSUE 19).

Every Goldilocks-specific literal that used to be sprinkled through the
transcript (8-byte absorb words, 64-bit challenge widths), FRI ((p+1)/2),
Merkle packing (4-element digests) and the cost model (8 bytes/element)
reads from here now — and the BabyBear backend is just a second instance
of the same record, selected by ``BOOJUM_TPU_FIELD={goldilocks,babybear}``
with Goldilocks the untouched default.

Why BabyBear: p = 2^31 - 2^27 + 1 fits ONE u32 lane per field element.
Goldilocks on TPU stores every element as a (lo, hi) u32 plane pair and
pays four cross-products plus a carry chain per multiply; BabyBear halves
the HBM/ICI/DCN bytes per element and multiplies in a single widened
product. Its two-adicity (27) clears every domain this repo builds
(2^10 traces, LDE factor <= 8), so the radix-2 NTT machinery applies
unchanged. The price is challenge soundness: 31-bit challenges are far
too small, so challenges/DEEP/FRI run over the degree-4 extension
GF(p^4) = GF(p)[x]/(x^4 - 11) (~124-bit ext order), where Goldilocks
needs only degree 2.

Stdlib-only at import time: transcripts, scripts and the report CLI read
these records without dragging in jax.
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class FieldSpec:
    name: str
    p: int
    two_adicity: int
    multiplicative_generator: int
    radix2_subgroup_generator: int  # primitive 2^two_adicity-th root of 1
    ext_degree: int  # extension degree challenges are drawn over
    ext_nonresidue: int  # GF(p^d) = GF(p)[x]/(x^d - ext_nonresidue)
    elem_bytes: int  # canonical on-device bytes per base element
    challenge_bits: int  # bits of one transcript challenge word
    digest_elems: int  # base elements per Merkle digest
    sponge_width: int  # Poseidon2 state width
    sponge_rate: int

    @property
    def half(self) -> int:
        """(p+1)/2 — the multiplicative inverse of 2 mod p."""
        return (self.p + 1) // 2

    @property
    def challenge_bytes(self) -> int:
        """LE word width a byte-oriented transcript absorbs one element as."""
        return (self.challenge_bits + 7) // 8

    @property
    def sponge_capacity(self) -> int:
        return self.sponge_width - self.sponge_rate

    def omega(self, log_n: int) -> int:
        """Primitive 2^log_n-th root of unity (two-adic tower)."""
        assert log_n <= self.two_adicity
        w = self.radix2_subgroup_generator
        for _ in range(self.two_adicity - log_n):
            w = (w * w) % self.p
        return w


GOLDILOCKS = FieldSpec(
    name="goldilocks",
    p=0xFFFFFFFF00000001,
    two_adicity=32,
    multiplicative_generator=7,
    radix2_subgroup_generator=0x185629DCDA58878C,
    ext_degree=2,
    ext_nonresidue=7,
    elem_bytes=8,  # one u64 (= two u32 limb planes on device)
    challenge_bits=64,
    digest_elems=4,
    sponge_width=12,
    sponge_rate=8,
)

_BB_P = (1 << 31) - (1 << 27) + 1  # 2013265921

BABYBEAR = FieldSpec(
    name="babybear",
    p=_BB_P,
    two_adicity=27,
    multiplicative_generator=31,
    # 31^((p-1)/2^27) mod p — the canonical two-adic generator
    radix2_subgroup_generator=pow(31, (_BB_P - 1) >> 27, _BB_P),
    ext_degree=4,  # 31-bit challenges are unsound; GF(p^4) ~ 2^124
    ext_nonresidue=11,  # x^4 - 11 is irreducible over GF(p)
    elem_bytes=4,  # ONE u32 lane — the whole point
    challenge_bits=31,
    digest_elems=8,
    sponge_width=16,
    sponge_rate=8,
)

SPECS = {s.name: s for s in (GOLDILOCKS, BABYBEAR)}

_ENV = "BOOJUM_TPU_FIELD"


def active_field() -> str:
    """The selected field backend name. Read from ``BOOJUM_TPU_FIELD`` at
    CALL time (not import time) so tests can flip it per-case; unset or
    empty means Goldilocks — the untouched default path."""
    v = os.environ.get(_ENV, "").strip().lower()
    if not v:
        return "goldilocks"
    if v not in SPECS:
        raise ValueError(
            f"{_ENV}={v!r}: unknown field backend (want one of "
            f"{sorted(SPECS)})"
        )
    return v


def active_spec() -> FieldSpec:
    return SPECS[active_field()]


def is_babybear() -> bool:
    return active_field() == "babybear"
