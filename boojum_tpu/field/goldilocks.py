"""Goldilocks field GF(p), p = 2^64 - 2^32 + 1, as batched JAX uint64 ops.

This is the TPU-native counterpart of the reference scalar/SIMD field layer
(`/root/reference/src/field/goldilocks/mod.rs:94`, `generic_impl.rs:13`). Where
the reference vectorizes 16 lanes with AVX-512, we express every op on whole
JAX arrays (any shape) and let XLA tile them onto the TPU vector units; u64 is
carried as XLA's emulated 64-bit integer pairs. All stored values are kept
canonical (in [0, p)).

The 128-bit product reduction is the standard Goldilocks identity
2^64 = 2^32 - 1 (mod p) (same algorithm family as the reference's
`from_u128_with_reduction`): with x = hi·2^64 + lo, hi = hh·2^32 + hl,
    x = lo - hh + hl·(2^32 - 1)  (mod p),
computed with explicit wrap/borrow fixups in uint64 arithmetic.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Constants
# ---------------------------------------------------------------------------

P_INT = 0xFFFFFFFF00000001  # 2^64 - 2^32 + 1
EPSILON_INT = 0xFFFFFFFF  # 2^32 - 1 == 2^64 mod p
MULTIPLICATIVE_GENERATOR_INT = 7
# Generator of the 2^32-order multiplicative subgroup
# (reference: src/field/goldilocks/mod.rs:107 RADIX_2_SUBGROUP_GENERATOR).
RADIX_2_SUBGROUP_GENERATOR_INT = 0x185629DCDA58878C
TWO_ADICITY = 32

_u64 = jnp.uint64
P = np.uint64(P_INT)
EPSILON = np.uint64(EPSILON_INT)
MASK32 = np.uint64(0xFFFFFFFF)
MULTIPLICATIVE_GENERATOR = np.uint64(MULTIPLICATIVE_GENERATOR_INT)
RADIX_2_SUBGROUP_GENERATOR = np.uint64(RADIX_2_SUBGROUP_GENERATOR_INT)


def to_field(x) -> jax.Array:
    """Lift python ints / numpy arrays into canonical uint64 field arrays."""
    arr = np.asarray(x, dtype=np.object_)
    arr = np.vectorize(lambda v: int(v) % P_INT, otypes=[np.uint64])(arr)
    return jnp.asarray(arr, dtype=_u64)


# ---------------------------------------------------------------------------
# Ring ops (all elementwise on arbitrary-shape uint64 arrays)
# ---------------------------------------------------------------------------


def add(a: jax.Array, b: jax.Array) -> jax.Array:
    s = a + b
    # on u64 overflow the true value is s + 2^64 ≡ s + EPSILON (mod p)
    s = jnp.where(s < a, s + EPSILON, s)
    return jnp.where(s >= P, s - P, s)


def sub(a: jax.Array, b: jax.Array) -> jax.Array:
    d = a - b
    # borrow: true value is d - 2^64 ≡ d - EPSILON (mod p)
    return jnp.where(a < b, d - EPSILON, d)


def neg(a: jax.Array) -> jax.Array:
    return jnp.where(a == 0, a, P - a)


def double(a: jax.Array) -> jax.Array:
    return add(a, a)


def mul_wide(a: jax.Array, b: jax.Array):
    """Full 64x64 -> 128-bit product as (hi, lo) uint64 pair."""
    a_lo = a & MASK32
    a_hi = a >> np.uint64(32)
    b_lo = b & MASK32
    b_hi = b >> np.uint64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = lh + hl
    mid_carry = (mid < lh).astype(_u64)
    lo = ll + (mid << np.uint64(32))
    lo_carry = (lo < ll).astype(_u64)
    hi = hh + (mid >> np.uint64(32)) + (mid_carry << np.uint64(32)) + lo_carry
    return hi, lo


def reduce128(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """Reduce a 128-bit value (hi·2^64 + lo) to a canonical field element."""
    hi_hi = hi >> np.uint64(32)
    hi_lo = hi & MASK32
    t0 = lo - hi_hi
    t0 = jnp.where(lo < hi_hi, t0 - EPSILON, t0)
    t1 = hi_lo * EPSILON  # < 2^64, no overflow
    t2 = t0 + t1
    res = jnp.where(t2 < t0, t2 + EPSILON, t2)
    return jnp.where(res >= P, res - P, res)


def mul(a: jax.Array, b: jax.Array) -> jax.Array:
    hi, lo = mul_wide(a, b)
    return reduce128(hi, lo)


def sqr(a: jax.Array) -> jax.Array:
    return mul(a, a)


def mul_small(a: jax.Array, k: int) -> jax.Array:
    """Multiply by a small constant via modular double-and-add (cheap on VPU)."""
    assert 0 <= k
    if k == 0:
        return jnp.zeros_like(a)
    acc = None
    addend = a
    while k:
        if k & 1:
            acc = addend if acc is None else add(acc, addend)
        k >>= 1
        if k:
            addend = double(addend)
    return acc


def pow_const(a: jax.Array, e: int) -> jax.Array:
    """a ** e for a python-int exponent (static square-and-multiply chain)."""
    e = int(e)
    assert e >= 0
    result = None
    base = a
    while e:
        if e & 1:
            result = base if result is None else mul(result, base)
        e >>= 1
        if e:
            base = sqr(base)
    if result is None:
        return jnp.ones_like(a)
    return result


@jax.jit
def inv(a: jax.Array) -> jax.Array:
    """Fermat inverse a^(p-2); inverse of 0 is 0 (callers must avoid it).

    Jitted: the square-and-multiply chain is ~90 muls — one compile per
    shape instead of ~1500 eager primitive dispatches per call."""
    return pow_const(a, P_INT - 2)


def prefix_product(a: jax.Array) -> jax.Array:
    """Inclusive modular prefix product along the last axis via log-doubling
    (Hillis–Steele): log2(n) rounds of shift+multiply. Deliberately NOT
    lax.associative_scan — its recursive slicing graph makes XLA compile
    time blow up on wide combine functions; this form compiles flat."""
    n = a.shape[-1]
    shift = 1
    while shift < n:
        ones = jnp.ones(a.shape[:-1] + (shift,), a.dtype)
        shifted = jnp.concatenate([ones, a[..., :-shift]], axis=-1)
        a = mul(a, shifted)
        shift *= 2
    return a


def batch_inverse(a: jax.Array) -> jax.Array:
    """Montgomery batch inversion along the last axis (log-doubling XLA
    scans; a sequential-tile Pallas block-scan was tried and measured ~10x
    slower on v5e — carry serialization defeats pipelining — so the XLA
    form is the single implementation)."""
    return batch_inverse_xla(a)


@jax.jit
def batch_inverse_xla(a: jax.Array) -> jax.Array:
    """Montgomery batch inversion along the last axis.

    Two modular prefix-product passes plus ONE Fermat inversion (the
    vectorized counterpart of the reference's serial Montgomery trick,
    `/root/reference/src/cs/implementations/utils.rs:405`).
    """
    prefix = prefix_product(a)
    total_inv = inv(prefix[..., -1:])
    rev = jnp.flip(a, axis=-1)
    rev_prefix = prefix_product(rev)
    # prod(a[i+1:]) = rev_prefix[n-2-i] for i < n-1, 1 for i = n-1
    suffix = jnp.concatenate(
        [jnp.flip(rev_prefix[..., :-1], axis=-1), jnp.ones_like(a[..., :1])],
        axis=-1,
    )
    shifted_prefix = jnp.concatenate(
        [jnp.ones_like(a[..., :1]), prefix[..., :-1]], axis=-1
    )
    return mul(mul(total_inv, suffix), shifted_prefix)
