from .goldilocks import (
    P_INT as P,  # python int: safe for user arithmetic (no numpy overflow)
    EPSILON,
    MULTIPLICATIVE_GENERATOR,
    TWO_ADICITY,
    RADIX_2_SUBGROUP_GENERATOR,
    add,
    sub,
    neg,
    mul,
    double,
    sqr,
    pow_const,
    inv,
    batch_inverse,
    to_field,
    mul_wide,
    reduce128,
)
from . import gl
from . import extension as ext
from . import limbs
from . import limb_ops
