"""Goldilocks field arithmetic on 2x uint32 limb pairs — the Pallas form.

TPU vector units have no 64-bit integer datapath: Mosaic (the Pallas TPU
compiler) rejects u64 values inside kernels, and XLA's u64 emulation cannot be
fused across kernel boundaries. This module is the 32-bit-limb field
representation the kernels compute in — the TPU counterpart of the reference's
per-ISA `MixedGL` backends (`/root/reference/src/field/goldilocks/
avx512_impl.rs`, `arm_asm_impl.rs`): where those pack 16 Goldilocks lanes into
AVX-512/NEON registers, these ops treat a field element as a pair of same-shape
uint32 arrays `(lo, hi)` and express add/sub/mul/reduce in pure `jnp` uint32
ops, so the SAME code runs inside Pallas kernels (VPU lanes over VMEM tiles)
and as plain XLA (CPU fallback / interpret-mode tests).

All scalar-level algorithms match `field/goldilocks.py` exactly (EPSILON
reduction, wrap/borrow fixups); values are kept canonical in [0, p). The
32x32->64 product uses a 16-bit split (4 VPU multiplies) because the TPU's
integer multiplier returns only the low 32 bits.
"""

from __future__ import annotations

import contextlib
import contextvars

import jax
import jax.numpy as jnp
import numpy as np

from . import gl

_u32 = jnp.uint32
U16_MASK = np.uint32(0xFFFF)
# p = 2^64 - 2^32 + 1 as limbs: lo = 1, hi = 0xFFFFFFFF
P_LO = np.uint32(1)
P_HI = np.uint32(0xFFFFFFFF)
EPS = np.uint32(0xFFFFFFFF)  # 2^32 - 1 == 2^64 mod p (fits one limb)


# ---------------------------------------------------------------------------
# u64 <-> limb conversions (run OUTSIDE kernels, plain XLA)
# ---------------------------------------------------------------------------
# Every device-side conversion is charged to the metrics registry (ISSUE 10):
# `limb.splits` / `limb.joins` are the INTERIOR boundary tax the resident
# mode exists to delete; conversions wrapped in `edge(label)` are the
# allowlisted API-edge set (H2D/setup ingest, transcript absorbs, query
# openings, proof serialization) and count as `limb.edge_splits` /
# `limb.edge_joins` instead. The guard test (tests/test_limb_resident.py)
# pins a resident prove at ZERO interior conversions. Counters tick at
# trace time for jitted graphs — exactly when a conversion enters a
# compiled module — and at call time for eager ops; both are what "this
# graph contains a conversion" means.

_EDGE_LABEL: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "boojum_tpu.limb_edge", default=None
)


@contextlib.contextmanager
def edge(label: str):
    """Mark enclosed split/join calls as allowlisted edge conversions."""
    token = _EDGE_LABEL.set(str(label))
    try:
        yield
    finally:
        _EDGE_LABEL.reset(token)


def edge_label() -> str | None:
    return _EDGE_LABEL.get()


def _charge(kind: str):
    from ..utils import metrics as _metrics

    lbl = _EDGE_LABEL.get()
    if lbl is None:
        _metrics.count(f"limb.{kind}s")
    else:
        _metrics.count(f"limb.edge_{kind}s")


def split(x: jax.Array):
    """uint64 array -> (lo, hi) uint32 pair."""
    _charge("split")
    return (
        (x & jnp.uint64(0xFFFFFFFF)).astype(_u32),
        (x >> jnp.uint64(32)).astype(_u32),
    )


def join(pair) -> jax.Array:
    """(lo, hi) uint32 pair -> uint64 array."""
    _charge("join")
    lo, hi = pair
    return lo.astype(jnp.uint64) | (hi.astype(jnp.uint64) << jnp.uint64(32))


def const_pair(value: int):
    """A python-int field constant as numpy uint32 scalars (kernel-bakeable)."""
    v = int(value) % gl.P
    return np.uint32(v & 0xFFFFFFFF), np.uint32(v >> 32)


def split_np(x: np.ndarray):
    """Host-side split for precomputed tables (never a device op; counted
    separately so the residency guard can tell host edges from interior
    device conversions)."""
    from ..utils import metrics as _metrics

    _metrics.count("limb.host_splits")
    x = np.asarray(x, dtype=np.uint64)
    return (
        (x & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        (x >> np.uint64(32)).astype(np.uint32),
    )


def join_np(lo, hi) -> np.ndarray:
    """Host-side join (query openings / transcript pulls land here: the
    resident prover fetches u32 planes and reassembles u64 on host)."""
    from ..utils import metrics as _metrics

    _metrics.count("limb.host_joins")
    return np.asarray(lo, dtype=np.uint64) | (
        np.asarray(hi, dtype=np.uint64) << np.uint64(32)
    )


# ---------------------------------------------------------------------------
# 32-bit building blocks
# ---------------------------------------------------------------------------


def _b2u(x) -> jax.Array:
    return x.astype(_u32)


def mul32_wide(a, b):
    """Full 32x32 -> 64-bit product as (lo, hi) uint32 pair.

    16-bit split: the exact high half fits uint32, so intermediate mod-2^32
    wraps cancel (the final values are exact)."""
    a0 = a & U16_MASK
    a1 = a >> 16
    b0 = b & U16_MASK
    b1 = b >> 16
    ll = a0 * b0
    lh = a0 * b1
    hl = a1 * b0
    hh = a1 * b1
    mid = lh + hl  # 33-bit true value; capture the wrap bit
    mid_c = _b2u(mid < lh)
    lo = ll + (mid << 16)
    lo_c = _b2u(lo < ll)
    hi = hh + (mid >> 16) + (mid_c << 16) + lo_c
    return lo, hi


def add64(a, b):
    """(lo, hi, carry) of a 64-bit add over limb pairs."""
    lo = a[0] + b[0]
    c = _b2u(lo < a[0])
    t = a[1] + b[1]
    c1 = _b2u(t < a[1])
    hi = t + c
    c2 = _b2u(hi < t)
    return lo, hi, c1 | c2


def sub64(a, b):
    """(lo, hi, borrow) of a 64-bit subtract over limb pairs."""
    lo = a[0] - b[0]
    br = _b2u(a[0] < b[0])
    t = a[1] - b[1]
    b1 = _b2u(a[1] < b[1])
    hi = t - br
    b2 = _b2u(t < br)
    return lo, hi, b1 | b2


def _plus_eps_where(lo, hi, cond):
    """(lo,hi) + EPSILON where cond (cond in {0,1} uint32).

    Adding 0xFFFFFFFF to lo = lo - 1 with carry-out iff lo != 0."""
    new_lo = lo - cond
    new_hi = hi + (cond & _b2u(lo != 0))
    return new_lo, new_hi


def _minus_eps_where(lo, hi, cond):
    """(lo,hi) - EPSILON where cond: lo + 1 with borrow-out iff lo == max."""
    new_lo = lo + cond
    new_hi = hi - (cond & _b2u(lo != EPS))
    return new_lo, new_hi


def _canonicalize(lo, hi):
    """Subtract p once where (lo,hi) >= p. Input < p + 2^32 (so one pass)."""
    ge = _b2u(hi == P_HI) & _b2u(lo >= P_LO)
    return lo - ge, jnp.where(ge, jnp.zeros_like(hi), hi)


# ---------------------------------------------------------------------------
# Field ops on limb pairs (canonical in, canonical out)
# ---------------------------------------------------------------------------


def add(a, b):
    lo, hi, c = add64(a, b)
    lo, hi = _plus_eps_where(lo, hi, c)
    return _canonicalize(lo, hi)


def sub(a, b):
    lo, hi, br = sub64(a, b)
    return _minus_eps_where(lo, hi, br)


def neg(a):
    z = jnp.zeros_like(a[0])
    return sub((z, z), a)


def double(a):
    return add(a, a)


def mul_wide(a, b):
    """Full 64x64 -> 128-bit product as 4 uint32 limbs (p0 lowest)."""
    ll_lo, ll_hi = mul32_wide(a[0], b[0])
    lh_lo, lh_hi = mul32_wide(a[0], b[1])
    hl_lo, hl_hi = mul32_wide(a[1], b[0])
    hh_lo, hh_hi = mul32_wide(a[1], b[1])
    s1 = ll_hi + lh_lo
    c1 = _b2u(s1 < ll_hi)
    p1 = s1 + hl_lo
    c2 = _b2u(p1 < s1)
    carry1 = c1 + c2  # 0..2
    s2 = lh_hi + hl_hi
    d1 = _b2u(s2 < lh_hi)
    s3 = s2 + hh_lo
    d2 = _b2u(s3 < s2)
    p2 = s3 + carry1
    d3 = _b2u(p2 < s3)
    p3 = hh_hi + d1 + d2 + d3
    return ll_lo, p1, p2, p3


def reduce128(p0, p1, p2, p3):
    """(p3·2^96 + p2·2^64 + p1·2^32 + p0) mod p, canonical.

    Same identity as goldilocks.reduce128: x ≡ lo64 - hi_hi + hi_lo·ε with
    hi_lo·ε = hi_lo·2^32 - hi_lo computed without a multiply."""
    # t0 = lo64 - p3 (64-bit), borrow -> -= EPSILON
    lo, hi, br = sub64((p0, p1), (p3, jnp.zeros_like(p3)))
    lo, hi = _minus_eps_where(lo, hi, br)
    # t1 = p2 * EPSILON = (p2 << 32) - p2
    nz = _b2u(p2 != 0)
    t1_lo = jnp.zeros_like(p2) - p2
    t1_hi = p2 - nz
    # t2 = t0 + t1, carry -> += EPSILON
    lo2, hi2, c = add64((lo, hi), (t1_lo, t1_hi))
    lo2, hi2 = _plus_eps_where(lo2, hi2, c)
    return _canonicalize(lo2, hi2)


def mul(a, b):
    return reduce128(*mul_wide(a, b))


def sqr(a):
    """a*a, sharing the cross product (12 VPU multiplies instead of 16)."""
    ll_lo, ll_hi = mul32_wide(a[0], a[0])
    lh_lo, lh_hi = mul32_wide(a[0], a[1])
    hh_lo, hh_hi = mul32_wide(a[1], a[1])
    # cross term appears twice: (lh << 32) * 2
    x_lo = lh_lo << 1
    xc0 = lh_lo >> 31
    x_hi = (lh_hi << 1) | xc0
    xc1 = lh_hi >> 31  # carry into p3
    s1 = ll_hi + x_lo
    c1 = _b2u(s1 < ll_hi)
    s2 = hh_lo + x_hi
    d1 = _b2u(s2 < hh_lo)
    p2 = s2 + c1
    d2 = _b2u(p2 < s2)
    p3 = hh_hi + xc1 + d1 + d2
    return reduce128(ll_lo, s1, p2, p3)


def mul_const(a, c_pair):
    """Multiply by a baked (np.uint32, np.uint32) constant pair."""
    clo, chi = c_pair
    b = (jnp.full_like(a[0], clo), jnp.full_like(a[1], chi))
    return mul(a, b)


# ---------------------------------------------------------------------------
# Quadratic extension GF(p^2) = GF(p)[w]/(w^2 - 7) on limb pairs
# ---------------------------------------------------------------------------

_SEVEN = (np.uint32(7), np.uint32(0))


def ext_add(a, b):
    return add(a[0], b[0]), add(a[1], b[1])


def ext_sub(a, b):
    return sub(a[0], b[0]), sub(a[1], b[1])


def ext_mul(a, b):
    """(a0 + a1 w)(b0 + b1 w) = a0b0 + 7 a1b1 + (a0b1 + a1b0) w."""
    v0 = mul(a[0], b[0])
    v1 = mul(a[1], b[1])
    t = mul(add(a[0], a[1]), add(b[0], b[1]))
    c1 = sub(t, add(v0, v1))
    c0 = add(v0, mul_const(v1, _SEVEN))
    return c0, c1
