"""BabyBear field arithmetic: one u32 lane = one field element (ISSUE 19).

p = 2^31 - 2^27 + 1 = 2013265921, two-adicity 27. Where Goldilocks stores a
(lo, hi) u32 plane pair per element and pays four cross-products plus the
reduce128 carry chain per multiply (`field/goldilocks.py`, `field/limbs.py`),
BabyBear is a single u32 lane: adds/subs are one conditional correction, a
multiply is one widened 62-bit product folded back to u32. Arrays are HALF
the HBM/ICI/DCN bytes of the limb-resident Goldilocks planes — the raw-speed
ceiling this backend exists to raise (ROADMAP open item 5).

Three layers, mirroring the Goldilocks split:
  - device array ops on jnp uint32 (this module's jnp functions),
  - host scalar ops over python ints (`*_s` helpers + module constants),
  - numpy vectorized host-table ops (`mul_np`, `powers_np`).

Challenge soundness: 31 bits is far too small a draw, so challenges, DEEP
and FRI run over the degree-4 tower GF(p^4) = GF(p)[x]/(x^4 - 11)
(~2^124 ext order; Goldilocks needs only degree 2). Extension elements are
4-tuples of base elements everywhere — (c0, c1, c2, c3) u32 arrays on
device, int 4-tuples on host.

All values canonical in [0, p). Products widen to u64 inside the XLA graph
(a compiler-internal detail — stored arrays stay bare u32; the HBM win is
the array bytes, not the ALU width).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .spec import BABYBEAR as SPEC

P = SPEC.p
TWO_ADICITY = SPEC.two_adicity
MULTIPLICATIVE_GENERATOR = SPEC.multiplicative_generator
RADIX_2_SUBGROUP_GENERATOR = SPEC.radix2_subgroup_generator
EXT_NONRESIDUE = SPEC.ext_nonresidue  # w^4 = 11

_P32 = np.uint32(P)
_P64 = np.uint64(P)


# ---------------------------------------------------------------------------
# Host scalar ops (python ints) — transcript, twiddle setup, verifier
# ---------------------------------------------------------------------------


def add_s(a: int, b: int) -> int:
    s = a + b
    return s - P if s >= P else s


def sub_s(a: int, b: int) -> int:
    d = a - b
    return d + P if d < 0 else d


def neg_s(a: int) -> int:
    return 0 if a == 0 else P - a


def mul_s(a: int, b: int) -> int:
    return (a * b) % P


def pow_s(a: int, e: int) -> int:
    return pow(a, e, P)


def inv_s(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("inverse of zero in BabyBear")
    return pow(a, P - 2, P)


def omega(log_n: int) -> int:
    """Primitive 2^log_n-th root of unity (two-adic tower)."""
    return SPEC.omega(log_n)


def powers(base: int, count: int) -> list:
    out = [1] * count
    for i in range(1, count):
        out[i] = mul_s(out[i - 1], base)
    return out


# ---------------------------------------------------------------------------
# NumPy vectorized host-table ops (twiddles, scale tables, reference prover)
# ---------------------------------------------------------------------------


def mul_np(a, b):
    """Canonical BabyBear multiply on uint32 numpy arrays. The product is
    < 2^62, so one u64 widening + remainder is exact — no carry chain."""
    a = np.asarray(a, dtype=np.uint64)
    b = np.asarray(b, dtype=np.uint64)
    return ((a * b) % _P64).astype(np.uint32)


def add_np(a, b):
    # conditional-subtract written without an underflowing where-branch so
    # numpy scalar inputs (reference-backend ext ops) stay warning-free
    s = np.asarray(a, dtype=np.uint32) + np.asarray(b, dtype=np.uint32)
    return s - np.where(s >= _P32, _P32, np.uint32(0))


def sub_np(a, b):
    # a + (p - b) < 2^32 for canonical inputs; fold back with one cond-sub
    r = np.asarray(a, dtype=np.uint32) + (
        _P32 - np.asarray(b, dtype=np.uint32)
    )
    return r - np.where(r >= _P32, _P32, np.uint32(0))


def powers_np(base: int, count: int):
    """[1, b, ..., b^(count-1)] as a uint32 numpy array (log-doubling)."""
    out = np.ones(count, dtype=np.uint32)
    if count <= 1:
        return out
    cur = 1
    while cur < count:
        step = np.uint32(pow_s(base, cur))
        nxt = min(cur, count - cur)
        out[cur : cur + nxt] = mul_np(out[:nxt], step)
        cur += nxt
    return out


# ---------------------------------------------------------------------------
# Device array ops on bare u32 lanes
# ---------------------------------------------------------------------------

_u32 = jnp.uint32
_u64 = jnp.uint64


def add(a, b):
    s = a + b  # a, b < p < 2^31: no u32 overflow
    return jnp.where(s >= _u32(P), s - _u32(P), s)


def sub(a, b):
    # wrapping u32: a - b + p is exact whichever side wraps
    return jnp.where(a >= b, a - b, a + (_u32(P) - b))


def neg(a):
    return jnp.where(a == 0, a, _u32(P) - a)


def double(a):
    return add(a, a)


def mul(a, b):
    """a*b mod p. One widened 62-bit product, one constant-divisor
    remainder (XLA strength-reduces it to a multiply-high chain)."""
    w = a.astype(_u64) * b.astype(_u64)
    return (w % _u64(P)).astype(_u32)


def sqr(a):
    return mul(a, a)


def mul_const(a, c: int):
    return mul(a, jnp.full_like(a, np.uint32(int(c) % P)))


@jax.jit
def pow_const(a, e):
    """a^e for a traced uint32 exponent array/scalar (square-and-multiply
    over the 31 exponent bits)."""
    e = jnp.asarray(e, dtype=_u32)

    def body(i, carry):
        acc, base = carry
        take = (e >> i) & _u32(1)
        acc = jnp.where(take == 1, mul(acc, base), acc)
        return acc, sqr(base)

    acc, _ = jax.lax.fori_loop(0, 31, body, (jnp.ones_like(a), a))
    return acc


@jax.jit
def inv(a):
    """Fermat: a^(p-2), addition-chain free (31 squarings + bit-selected
    multiplies against the fixed exponent p-2)."""
    e = P - 2
    acc = jnp.ones_like(a)
    base = a
    for i in range(31):
        if (e >> i) & 1:
            acc = mul(acc, base)
        if i != 30:
            base = sqr(base)
    return acc


def prefix_product(x):
    """Inclusive prefix products along the last axis, log-depth
    (Hillis–Steele doubling — same shape as goldilocks.prefix_product:
    field mul is NOT associative-scan-safe under XLA's reassociation
    assumptions, so the doubling is explicit)."""
    n = x.shape[-1]
    steps = max(1, (n - 1).bit_length())
    y = x
    for s in range(steps):
        shift = 1 << s
        ones = jnp.ones_like(y[..., :shift])
        shifted = jnp.concatenate([ones, y[..., :-shift]], axis=-1)
        y = mul(y, shifted)
    return y


@jax.jit
def batch_inverse_xla(x):
    """Montgomery's trick: two prefix-product sweeps + ONE Fermat
    inversion, all on device — the BabyBear twin of
    goldilocks.batch_inverse_xla."""
    pref = prefix_product(x)
    total_inv = inv(pref[..., -1:])
    ones = jnp.ones_like(x[..., :1])
    pref_prev = jnp.concatenate([ones, pref[..., :-1]], axis=-1)
    # suffix product of the tail via reversed prefix products
    rev = jnp.flip(x, axis=-1)
    suff = jnp.concatenate(
        [jnp.flip(prefix_product(rev), axis=-1)[..., 1:], ones], axis=-1
    )
    return mul(mul(pref_prev, suff), total_inv)


# ---------------------------------------------------------------------------
# Degree-4 extension GF(p^4) = GF(p)[w]/(w^4 - 11)
# Elements are 4-tuples (c0, c1, c2, c3); device tuples hold u32 arrays,
# host `_s` tuples hold python ints.
# ---------------------------------------------------------------------------

ZERO_S = (0, 0, 0, 0)
ONE_S = (1, 0, 0, 0)


def ext_from_base_s(a: int):
    return (int(a) % P, 0, 0, 0)


def ext_add_s(a, b):
    return tuple(add_s(x, y) for x, y in zip(a, b))


def ext_sub_s(a, b):
    return tuple(sub_s(x, y) for x, y in zip(a, b))


def ext_neg_s(a):
    return tuple(neg_s(x) for x in a)


def ext_mul_s(a, b):
    """Schoolbook with w^4 = 11: c_k = sum_{i+j=k} a_i b_j
    + 11 * sum_{i+j=k+4} a_i b_j."""
    a0, a1, a2, a3 = a
    b0, b1, b2, b3 = b
    nr = EXT_NONRESIDUE
    c0 = (a0 * b0 + nr * (a1 * b3 + a2 * b2 + a3 * b1)) % P
    c1 = (a0 * b1 + a1 * b0 + nr * (a2 * b3 + a3 * b2)) % P
    c2 = (a0 * b2 + a1 * b1 + a2 * b0 + nr * (a3 * b3)) % P
    c3 = (a0 * b3 + a1 * b2 + a2 * b1 + a3 * b0) % P
    return (c0, c1, c2, c3)


def ext_scale_s(a, k: int):
    return tuple(mul_s(x, k % P) for x in a)


def ext_pow_s(a, e: int):
    acc = ONE_S
    base = a
    while e:
        if e & 1:
            acc = ext_mul_s(acc, base)
        base = ext_mul_s(base, base)
        e >>= 1
    return acc


def ext_inv_s(a):
    """Fermat over the extension: a^(p^4 - 2). ~250 ext muls of host ints
    — transcript-scale, never device-scale."""
    if all(x == 0 for x in a):
        raise ZeroDivisionError("inverse of zero in GF(p^4)")
    return ext_pow_s(a, P**4 - 2)


# --- device ext ops (tuples of u32 arrays) ---------------------------------


def ext_zero_like(x):
    z = jnp.zeros_like(x)
    return (z, z, z, z)


def ext_add(a, b):
    return tuple(add(x, y) for x, y in zip(a, b))


def ext_sub(a, b):
    return tuple(sub(x, y) for x, y in zip(a, b))


def ext_neg(a):
    return tuple(neg(x) for x in a)


def ext_mul(a, b):
    """16 base muls + folds; the nonresidue fold is a constant mul."""
    a0, a1, a2, a3 = a
    b0, b1, b2, b3 = b
    nr = np.uint32(EXT_NONRESIDUE)

    def _nr(x):
        return mul(x, jnp.full_like(x, nr))

    c0 = add(mul(a0, b0), _nr(add(add(mul(a1, b3), mul(a2, b2)), mul(a3, b1))))
    c1 = add(add(mul(a0, b1), mul(a1, b0)), _nr(add(mul(a2, b3), mul(a3, b2))))
    c2 = add(add(mul(a0, b2), mul(a1, b1)), add(mul(a2, b0), _nr(mul(a3, b3))))
    c3 = add(add(mul(a0, b3), mul(a1, b2)), add(mul(a2, b1), mul(a3, b0)))
    return (c0, c1, c2, c3)


def ext_scale(a, k):
    """ext * base (base may be an array or a baked constant int)."""
    if isinstance(k, (int, np.integer)):
        return tuple(mul_const(x, int(k)) for x in a)
    return tuple(mul(x, k) for x in a)


def ext_const(c, like):
    """A host ext 4-tuple as device arrays broadcast like `like`."""
    return tuple(jnp.full_like(like, np.uint32(int(x) % P)) for x in c)


# --- numpy ext twins (reference prover) ------------------------------------


def ext_add_np(a, b):
    return tuple(add_np(x, y) for x, y in zip(a, b))


def ext_sub_np(a, b):
    return tuple(sub_np(x, y) for x, y in zip(a, b))


def ext_mul_np(a, b):
    a0, a1, a2, a3 = a
    b0, b1, b2, b3 = b
    nr = np.uint32(EXT_NONRESIDUE)
    c0 = add_np(
        mul_np(a0, b0),
        mul_np(
            add_np(add_np(mul_np(a1, b3), mul_np(a2, b2)), mul_np(a3, b1)),
            nr,
        ),
    )
    c1 = add_np(
        add_np(mul_np(a0, b1), mul_np(a1, b0)),
        mul_np(add_np(mul_np(a2, b3), mul_np(a3, b2)), nr),
    )
    c2 = add_np(
        add_np(mul_np(a0, b2), mul_np(a1, b1)),
        add_np(mul_np(a2, b0), mul_np(mul_np(a3, b3), nr)),
    )
    c3 = add_np(
        add_np(mul_np(a0, b3), mul_np(a1, b2)),
        add_np(mul_np(a2, b1), mul_np(a3, b0)),
    )
    return (c0, c1, c2, c3)


def inv_np(a):
    """Vectorized Fermat a^(p-2) on uint32 numpy arrays (31-step chain,
    the numpy twin of the device `inv`)."""
    a = np.asarray(a, dtype=np.uint32)
    e = P - 2
    acc = np.ones_like(a)
    base = a
    for i in range(31):
        if (e >> i) & 1:
            acc = mul_np(acc, base)
        if i != 30:
            base = mul_np(base, base)
    return acc


# w^p = FROB_C * w where FROB_C = 11^((p-1)/4): Frobenius is coordinate-wise
# multiplication by powers of a 4th root of unity — the device inverse
# below rides on it (3 constant-mul maps + 3 ext muls + ONE base Fermat
# instead of a 124-bit ext exponentiation).
_FROB_C = pow(EXT_NONRESIDUE, (P - 1) // 4, P)
_FROB_COEFFS = {
    k: tuple(pow(_FROB_C, (i * k) % 4, P) for i in range(4)) for k in (1, 2, 3)
}


def ext_frobenius_s(a, k: int):
    return tuple(mul_s(x, c) for x, c in zip(a, _FROB_COEFFS[k]))


def ext_frobenius(a, k: int):
    return tuple(
        x if c == 1 else mul_const(x, c)
        for x, c in zip(a, _FROB_COEFFS[k])
    )


def ext_inv(a):
    """Vectorized device inverse in GF(p^4) via the norm map:
    a^-1 = (a^p * a^p2 * a^p3) / N(a), N(a) = a * a^p * a^p2 * a^p3 in
    GF(p). Cost: 2 ext muls + one c0-row of a third + 3 Frobenius constant
    maps + ONE base-field Fermat inversion."""
    t = ext_mul(
        ext_frobenius(a, 1), ext_mul(ext_frobenius(a, 2), ext_frobenius(a, 3))
    )
    a0, a1, a2, a3 = a
    t0, t1, t2, t3 = t
    nr = np.uint32(EXT_NONRESIDUE)
    norm = add(
        mul(a0, t0),
        mul(
            add(add(mul(a1, t3), mul(a2, t2)), mul(a3, t1)),
            jnp.full_like(a0, nr),
        ),
    )
    return ext_scale(t, inv(norm))


def ext_inv_np(a):
    """Numpy twin of the device ext_inv (same Frobenius/norm shape)."""
    frobs = [
        tuple(mul_np(x, np.uint32(c)) for x, c in zip(a, _FROB_COEFFS[k]))
        for k in (1, 2, 3)
    ]
    t = ext_mul_np(frobs[0], ext_mul_np(frobs[1], frobs[2]))
    a0, a1, a2, a3 = a
    t0, t1, t2, t3 = t
    nr = np.uint32(EXT_NONRESIDUE)
    norm = add_np(
        mul_np(a0, t0),
        mul_np(
            add_np(add_np(mul_np(a1, t3), mul_np(a2, t2)), mul_np(a3, t1)),
            nr,
        ),
    )
    ninv = inv_np(norm)
    return tuple(mul_np(x, ninv) for x in t)


def ext_prefix_product(a):
    """Inclusive prefix products of a GF(p^4) vector (4-tuple of device
    arrays) along the last axis — Hillis–Steele doubling with ext_mul,
    the extension twin of prefix_product (ISSUE 20 stage-2 z column)."""
    n = a[0].shape[-1]
    steps = max(1, (n - 1).bit_length())
    y = a
    for s in range(steps):
        shift = 1 << s
        shifted = tuple(
            jnp.concatenate(
                [
                    (jnp.ones_like if k == 0 else jnp.zeros_like)(
                        y[k][..., :shift]
                    ),
                    y[k][..., :-shift],
                ],
                axis=-1,
            )
            for k in range(4)
        )
        y = ext_mul(y, shifted)
    return y


def ext_prefix_product_np(a):
    """Sequential numpy twin of ext_prefix_product (reference backend)."""
    n = int(a[0].shape[-1])
    out = tuple(np.empty_like(x) for x in a)
    shape = a[0][..., :1].shape
    cur = (
        np.ones(shape, dtype=np.uint32),
        np.zeros(shape, dtype=np.uint32),
        np.zeros(shape, dtype=np.uint32),
        np.zeros(shape, dtype=np.uint32),
    )
    for j in range(n):
        cur = ext_mul_np(cur, tuple(x[..., j : j + 1] for x in a))
        for k in range(4):
            out[k][..., j : j + 1] = cur[k]
    return out
