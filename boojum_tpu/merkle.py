"""Merkle tree with cap over Poseidon2 digests — device construction.

Counterpart of `/root/reference/src/cs/oracle/merkle_tree.rs:17` (construct
`:78`, get_proof `:462`, verify_proof_over_cap `:482`). Leaves are rows of a
(num_leaves, leaf_width) device array (all committed columns evaluated at one
LDE point, in full-domain bit-reversed enumeration); leaf hashing is one
batched sponge over the whole array, node layers are batched 2-to-1 hashes.
The cap (top 2^k nodes) replaces the single root. Query-path extraction
gathers from the stored device layers on host at query time (queries are rare:
~100 per proof).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .field import limbs as _limbs
from .hashes.poseidon2 import (
    Poseidon2SpongeHost,
    leaf_hash,
    leaf_hash_planes,
    node_hash,
    node_hash_planes,
)
from .parallel.sharding import host_np as _host_np
from .utils import metrics as _metrics


# Levels at or below this node count are fused into one compiled graph:
# the tail of a tree is ~log2(N) tiny dispatches whose round-trip latency
# dominates behind a network-tunneled device, while the big bottom levels
# amortize their dispatch over real compute (and fusing THEM produced
# modules too large for the remote compile service).
_FUSE_THRESHOLD = 1 << 12


@partial(jax.jit, static_argnums=(1,))
def _tree_tail_layers(digests, cap_size: int):
    """All remaining (small) node layers in one compiled graph."""
    layers = []
    cur = digests
    while cur.shape[0] > cap_size:
        cur = node_hash(cur[0::2], cur[1::2])
        layers.append(cur)
    return tuple(layers)


def _node_layers(digests, cap_size: int):
    """Digest layers from leaf digests up to the cap (shared by the
    materialized and streamed-commit paths)."""
    layers = [digests]
    while (
        layers[-1].shape[0] > cap_size
        and layers[-1].shape[0] > _FUSE_THRESHOLD
    ):
        cur = layers[-1]
        layers.append(node_hash(cur[0::2], cur[1::2]))
    if layers[-1].shape[0] > cap_size:
        layers.extend(_tree_tail_layers(layers[-1], cap_size))
    return tuple(layers)


def _tree_layers(leaf_values, cap_size: int):
    return _node_layers(leaf_hash(leaf_values), cap_size)


# ---------------------------------------------------------------------------
# Shape-keyed commit kernels (the compile-bill split, ISSUE 1)
# ---------------------------------------------------------------------------
# The fused one-graph-per-commit form (`_commit_fused`) paid a 200s+ remote
# compile PER ORACLE SHAPE because the NTTs, the leaf sponge and the node
# layers all landed in one module. Split, each sub-graph compiles in well
# under a minute AND the node-layer stack — keyed only on (num_leaves, cap),
# not on the oracle's column count — is compiled ONCE and shared by the
# witness/stage-2/quotient/setup commits and the streamed-digest path.


@jax.jit
def leaf_digests_device(lde_cols):
    """(B, ...) committed columns -> (N, 4) leaf digests, one dispatch.

    Accepts the prover's (B, L, n) LDE stacks or already-flat (B, N)
    columns; the leaf-major transpose happens inside the graph so no
    intermediate (N, B) matrix is ever dispatched eagerly. Keyed on the
    column stack shape."""
    B = lde_cols.shape[0]
    return leaf_hash(lde_cols.reshape(B, -1).T)


@partial(jax.jit, static_argnums=(1,))
def node_layers_device(digests, cap_size: int):
    """(N, 4) leaf digests -> all node layers up to the cap, one dispatch.

    Keyed only on (N, cap): every oracle of the same domain size reuses the
    same executable regardless of how many columns it commits."""
    return _node_layers(digests, cap_size)


def commit_layers_device(lde_cols, cap_size: int):
    """Column stack -> digest layers (leaves first, cap last) as two
    shape-keyed dispatches: leaf sponge + shared node stack."""
    _metrics.count("merkle.commit_layer_builds")
    return node_layers_device(leaf_digests_device(lde_cols), cap_size)


# ---------------------------------------------------------------------------
# Limb-plane commit kernels + tree (ISSUE 10): digests stay (lo, hi) u32
# plane pairs on device end-to-end; u64 exists only on HOST — the cap join
# and query-path joins happen in numpy at the transcript/query API edge.
# ---------------------------------------------------------------------------


@jax.jit
def leaf_digests_planes(lde_p):
    """Plane twin of leaf_digests_device: (B, ...) column planes ->
    (N, 4) digest planes, one dispatch."""
    lo, hi = lde_p
    B = lo.shape[0]
    return leaf_hash_planes((lo.reshape(B, -1).T, hi.reshape(B, -1).T))


@partial(jax.jit, static_argnums=(1,))
def _tree_tail_layers_planes(digests_p, cap_size: int):
    layers = []
    cur = digests_p
    while cur[0].shape[0] > cap_size:
        cur = node_hash_planes(
            (cur[0][0::2], cur[1][0::2]), (cur[0][1::2], cur[1][1::2])
        )
        layers.append(cur)
    return tuple(layers)


def _node_layers_planes_body(digests_p, cap_size: int):
    layers = [digests_p]
    while (
        layers[-1][0].shape[0] > cap_size
        and layers[-1][0].shape[0] > _FUSE_THRESHOLD
    ):
        cur = layers[-1]
        layers.append(
            node_hash_planes(
                (cur[0][0::2], cur[1][0::2]), (cur[0][1::2], cur[1][1::2])
            )
        )
    if layers[-1][0].shape[0] > cap_size:
        layers.extend(_tree_tail_layers_planes(layers[-1], cap_size))
    return tuple(layers)


@partial(jax.jit, static_argnums=(1,))
def node_layers_planes(digests_p, cap_size: int):
    """Plane twin of node_layers_device (same shared-executable keying)."""
    return _node_layers_planes_body(digests_p, cap_size)


def commit_layers_planes(lde_p, cap_size: int):
    """Plane twin of commit_layers_device."""
    _metrics.count("merkle.commit_layer_builds")
    return node_layers_planes(leaf_digests_planes(lde_p), cap_size)


def _cap_host_from_planes(cap_p):
    cap = _limbs.join_np(_host_np(cap_p[0]), _host_np(cap_p[1]))
    return [tuple(int(x) for x in row) for row in cap]


class PlaneMerkleTree:
    """MerkleTreeWithCap twin whose digest layers stay u32 plane pairs.

    Caps and query paths leave the device as planes and join on HOST
    (numpy) — the representation's API edge. Digest VALUES are identical
    to the u64 tree's, so transcripts and proofs are unchanged."""

    @classmethod
    def from_layers(cls, layers, cap_size: int) -> "PlaneMerkleTree":
        tree = cls.__new__(cls)
        tree.cap_size = cap_size
        tree.num_leaves = int(layers[0][0].shape[0])
        _metrics.count("merkle.tree_builds")
        _metrics.count("merkle.plane_tree_builds")
        tree.layers = list(layers)
        tree._cap_host = _cap_host_from_planes(tree.layers[-1])
        return tree

    @classmethod
    def from_digests(cls, digests_p, cap_size: int) -> "PlaneMerkleTree":
        n = int(digests_p[0].shape[0])
        assert n & (n - 1) == 0 and cap_size & (cap_size - 1) == 0
        assert n >= cap_size
        return cls.from_layers(
            list(node_layers_planes(digests_p, cap_size)), cap_size
        )

    def get_cap(self):
        return list(self._cap_host)

    def proof_gather_plans(self, leaf_indices):
        """Like MerkleTreeWithCap.proof_gather_plans, but each level
        contributes TWO plans (lo, hi); assemble() joins pairs on host."""
        idxs = np.array(list(leaf_indices), dtype=np.int64)
        plans = []
        cur = idxs
        for lo, hi in self.layers[:-1]:
            sib = cur ^ 1
            plans.append((lo, sib))
            plans.append((hi, sib))
            cur = cur >> 1

        def assemble(levels):
            joined = [
                _limbs.join_np(levels[2 * i], levels[2 * i + 1])
                for i in range(len(levels) // 2)
            ]
            return [
                [tuple(int(x) for x in level[q]) for level in joined]
                for q in range(len(idxs))
            ]

        return plans, assemble


class MerkleTreeWithCap:
    def __init__(self, leaf_values, cap_size: int, num_elems_per_leaf: int = 1):
        """leaf_values: (num_leaves, leaf_width) uint64 device array.

        num_elems_per_leaf > 1 groups that many adjacent rows into one leaf
        (used by FRI intermediate oracles, mirroring the reference's
        `num_elements_per_leaf`); leaf width becomes width*num_elems.
        """
        assert cap_size & (cap_size - 1) == 0
        n = leaf_values.shape[0]
        if num_elems_per_leaf > 1:
            leaf_values = leaf_values.reshape(
                n // num_elems_per_leaf, -1
            )
        self.num_leaves = leaf_values.shape[0]
        assert self.num_leaves & (self.num_leaves - 1) == 0, "leaf count must be 2^k"
        assert self.num_leaves >= cap_size
        self.cap_size = cap_size
        _metrics.count("merkle.tree_builds")
        self.layers = list(_tree_layers(leaf_values, cap_size))
        self._cap_host = [
            tuple(int(x) for x in row) for row in _host_np(self.layers[-1])
        ]

    @classmethod
    def from_digests(cls, digests, cap_size: int) -> "MerkleTreeWithCap":
        """Build the node layers over precomputed (num_leaves, 4) leaf
        digests — the streamed-commit path hashes leaves in column blocks
        (absorbing 8 columns at a time into a carried sponge state) and
        hands the finished digests here, so a full (num_leaves, width)
        leaf matrix never materializes."""
        tree = cls.__new__(cls)
        n = int(digests.shape[0])
        assert n & (n - 1) == 0, "leaf count must be 2^k"
        assert cap_size & (cap_size - 1) == 0 and n >= cap_size
        tree.cap_size = cap_size
        tree.num_leaves = n
        _metrics.count("merkle.tree_builds")
        tree.layers = list(node_layers_device(digests, cap_size))
        tree._cap_host = [
            tuple(int(x) for x in row) for row in _host_np(tree.layers[-1])
        ]
        return tree

    @classmethod
    def from_layers(cls, layers, cap_size: int) -> "MerkleTreeWithCap":
        """Rebuild a tree from precomputed digest layers (setup fast
        deserialization — no rehashing, reference fast_serialization.rs)."""
        tree = cls.__new__(cls)
        tree.cap_size = cap_size
        tree.num_leaves = int(layers[0].shape[0])
        _metrics.count("merkle.tree_builds")
        tree.layers = list(layers)
        tree._cap_host = [
            tuple(int(x) for x in row) for row in _host_np(layers[-1])
        ]
        return tree

    def get_cap(self):
        return list(self._cap_host)

    def proof_gather_plans(self, leaf_indices):
        """Like proof_gathers, but returns (layer, sibling-index) PLANS
        without dispatching any device op — the prover executes every
        oracle's plans in one fused gather (see prover._gather_flat_fused)."""
        idxs = np.array(list(leaf_indices), dtype=np.int64)
        plans = []
        cur = idxs
        for layer in self.layers[:-1]:
            plans.append((layer, cur ^ 1))
            cur = cur >> 1

        def assemble(levels):
            return [
                [tuple(int(x) for x in level[q]) for level in levels]
                for q in range(len(idxs))
            ]

        return plans, assemble

    def proof_gathers(self, leaf_indices):
        """Dispatch the per-level sibling gathers WITHOUT transferring:
        returns (lazy device arrays, assemble(levels) -> paths)."""
        plans, assemble = self.proof_gather_plans(leaf_indices)
        pending = [layer[jnp.asarray(ix)] for layer, ix in plans]
        return pending, assemble

    def get_proofs(self, leaf_indices):
        """Batched path extraction for many queries: ONE device gather per
        tree level (a (num_queries, 4) slice) instead of per-query
        per-level element reads — behind a network tunnel the round-trips
        dominate, on local hardware it is still fewer, larger transfers.
        Returns a list of paths aligned with leaf_indices."""
        pending, assemble = self.proof_gathers(leaf_indices)
        levels = [_host_np(x) for x in pending]
        return assemble(levels)

    def get_proof(self, leaf_idx: int):
        """Single-query path (see get_proofs for the batched form)."""
        return self.get_proofs([leaf_idx])[0]


def verify_proof_over_cap(leaf_values, path, cap, leaf_idx: int) -> bool:
    """Host-side path verification (python ints), reference `:482` semantics."""
    digest = Poseidon2SpongeHost.hash_leaf([int(v) for v in leaf_values])
    idx = leaf_idx
    for sib in path:
        if idx & 1:
            digest = Poseidon2SpongeHost.hash_node(sib, digest)
        else:
            digest = Poseidon2SpongeHost.hash_node(digest, sib)
        idx >>= 1
    return tuple(digest) == tuple(cap[idx])
