"""Small example circuits shared by tests, the driver entry, and docs.

The xor4 lookup circuit mirrors the shape of the reference's small lookup
tests (specialized columns, two tables, an FMA accumulator and one public
input) at toy scale; it exercises every prover round incl. the lookup paths.
"""

from __future__ import annotations

import numpy as np

from .cs.types import CSGeometry, LookupParameters
from .cs.implementations import ConstraintSystem
from .cs.lookup_table import LookupTable, range_check_table
from .cs.gates import FmaGate, PublicInputGate
from .cs.gates.simple import (
    MatrixMultiplicationGate,
    SimpleNonlinearityGate,
)

EXAMPLE_GEOMETRY = CSGeometry(
    num_columns_under_copy_permutation=8,
    num_witness_columns=0,
    num_constant_columns=6,
    max_allowed_constraint_degree=4,
)

EXAMPLE_LOOKUP = LookupParameters(width=3, num_repetitions=2)


def xor4_table() -> LookupTable:
    a = np.arange(16, dtype=np.uint64).repeat(16)
    b = np.tile(np.arange(16, dtype=np.uint64), 16)
    return LookupTable("xor4", 2, 1, np.stack([a, b, a ^ b], axis=1))


def build_xor_lookup_circuit(
    num_lookups: int = 30,
    geometry: CSGeometry = EXAMPLE_GEOMETRY,
    lookup_params: LookupParameters = EXAMPLE_LOOKUP,
    capacity: int = 1 << 10,
    seed: int = 7,
):
    """xor4 lookups + range checks chained through an FMA accumulator.

    Returns (cs, acc_var, last_lookup_out_var).
    """
    cs = ConstraintSystem(geometry, capacity, lookup_params=lookup_params)
    xor_id = cs.add_lookup_table(xor4_table())
    rc_id = cs.add_lookup_table(range_check_table(4))
    rng = np.random.default_rng(seed)
    acc = cs.alloc_variable_with_value(1)
    last_out = None
    for _ in range(num_lookups):
        a = cs.alloc_variable_with_value(int(rng.integers(16)))
        b = cs.alloc_variable_with_value(int(rng.integers(16)))
        (out,) = cs.perform_lookup(xor_id, [a, b])
        cs.enforce_lookup(rc_id, [out, cs.zero_var()])
        acc = FmaGate.fma(cs, acc, out, a, 1, 1)
        last_out = out
    PublicInputGate.place(cs, acc)
    return cs, acc, last_out


def build_fma_chain_circuit(
    num_rows: int = (1 << 10) - 8,
    geometry: CSGeometry = EXAMPLE_GEOMETRY,
    capacity: int = 1 << 10,
):
    """A Fibonacci-style fma chain with one public input: the minimal
    every-round circuit (no lookups). Field-agnostic arithmetic — the
    canonical e2e leg for alternative field backends (ISSUE 20).

    Returns (cs, out_var).
    """
    cs = ConstraintSystem(geometry, capacity)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geometry)
    for _ in range(num_rows * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    return cs, b


def build_poseidon_rf_circuit(
    num_rounds: int = 48,
    geometry: CSGeometry = EXAMPLE_GEOMETRY,
    capacity: int = 1 << 10,
    seed: int = 11,
):
    """A toy Poseidon-style round function: width-3 state, per round a
    degree-7 S-box with a round constant followed by a circulant MDS mix
    (SimpleNonlinearityGate + MatrixMultiplicationGate — the same gate
    shapes real Poseidon circuits use). Degree-7 constraints push the
    quotient degree to 8, exercising the decoupled sweep rate; all
    arithmetic fits any backend field (ISSUE 20's poseidon-rf e2e leg).

    Returns (cs, out_var).
    """
    cs = ConstraintSystem(geometry, capacity)
    rng = np.random.default_rng(seed)
    mds = MatrixMultiplicationGate(
        "rf3", [[2, 1, 1], [1, 2, 1], [1, 1, 2]]
    )
    state = [cs.alloc_variable_with_value(int(v)) for v in (3, 5, 7)]
    for _ in range(num_rounds):
        sboxed = [
            SimpleNonlinearityGate.apply(cs, x, int(rng.integers(1, 997)))
            for x in state
        ]
        state = mds.apply(cs, sboxed)
    PublicInputGate.place(cs, state[0])
    return cs, state[0]
