"""Artifact (de)serialization: VK JSON, proof JSON, setup fast format.

Counterpart of the reference's `MemcopySerializable` memcpy-style setup
serialization (`/root/reference/src/cs/implementations/fast_serialization.rs:12`,
impls in `polynomial_storage.rs:85,159`) and the serde JSON proof/VK artifacts
(`proof.json` / `vk.json` at the reference repo root). Setup storages are
dense numpy arrays here, so the "memcpy format" is a single `.npz` holding
every array (including the precomputed Merkle layers — loading re-uploads to
device without recomputing anything)."""

from __future__ import annotations

import json

import numpy as np

from .cs.types import CSGeometry, LookupParameters
from .merkle import MerkleTreeWithCap
from .prover.setup import SetupData, VerificationKey


# -- verification key --------------------------------------------------------


def vk_to_json(vk: VerificationKey) -> str:
    return json.dumps(vk.to_dict())


def vk_from_json(s: str) -> VerificationKey:
    d = json.loads(s)
    geometry = CSGeometry(**d["geometry"])
    lp = d.get("lookup_params")
    lookup_params = LookupParameters(**lp) if lp else None
    return VerificationKey(
        geometry=geometry,
        trace_len=int(d["trace_len"]),
        fri_lde_factor=int(d["fri_lde_factor"]),
        cap_size=int(d["cap_size"]),
        num_queries=int(d["num_queries"]),
        pow_bits=int(d["pow_bits"]),
        fri_final_degree=int(d["fri_final_degree"]),
        gate_names=list(d["gate_names"]),
        selector_paths=[list(p) for p in d["selector_paths"]],
        public_input_locations=[tuple(x) for x in d["public_input_locations"]],
        setup_merkle_cap=[tuple(int(v) for v in c) for c in d["setup_merkle_cap"]],
        num_copy_cols=int(d["num_copy_cols"]),
        num_wit_cols=int(d["num_wit_cols"]),
        lookup_params=lookup_params,
        num_lookup_tables=int(d.get("num_lookup_tables", 0)),
        fri_folding_schedule=d.get("fri_folding_schedule"),
        quotient_degree=(
            int(d["quotient_degree"])
            if d.get("quotient_degree") is not None
            else None
        ),
        transcript=_checked_transcript(d.get("transcript", "poseidon2")),
    )


def _checked_transcript(kind: str) -> str:
    from .transcript import TRANSCRIPTS

    if kind not in TRANSCRIPTS:
        raise ValueError(f"unknown transcript kind in vk: {kind!r}")
    return kind


# -- setup fast serialization ------------------------------------------------


def save_setup(path: str, setup: SetupData):
    """One .npz with every dense array + the VK as embedded JSON."""
    arrays = {
        "sigma_cols": np.asarray(setup.sigma_cols),
        "constant_cols": np.asarray(setup.constant_cols),
        "setup_monomials": np.asarray(setup.setup_monomials),
        # streamed-mode setups carry no materialized LDE (rebuilt lazily)
        **(
            {"setup_lde": np.asarray(setup.setup_lde)}
            if setup.setup_lde is not None
            else {}
        ),
        "non_residues": np.asarray(setup.non_residues, dtype=np.uint64),
        "vk_json": np.frombuffer(
            vk_to_json(setup.vk).encode(), dtype=np.uint8
        ),
        "tree_num_layers": np.asarray(
            [len(setup.setup_tree.layers)], dtype=np.int64
        ),
        "tree_cap_size": np.asarray(
            [setup.setup_tree.cap_size], dtype=np.int64
        ),
    }
    for i, layer in enumerate(setup.setup_tree.layers):
        arrays[f"tree_layer_{i}"] = np.asarray(layer)
    np.savez(path, **arrays)


def load_setup(path: str) -> SetupData:
    import jax.numpy as jnp

    with np.load(path) as z:
        vk = vk_from_json(bytes(z["vk_json"]).decode())
        num_layers = int(z["tree_num_layers"][0])
        cap_size = int(z["tree_cap_size"][0])
        layers = [jnp.asarray(z[f"tree_layer_{i}"]) for i in range(num_layers)]
        tree = MerkleTreeWithCap.from_layers(layers, cap_size)
        return SetupData(
            vk=vk,
            sigma_cols=z["sigma_cols"],
            constant_cols=z["constant_cols"],
            setup_monomials=jnp.asarray(z["setup_monomials"]),
            setup_lde=(
                jnp.asarray(z["setup_lde"]) if "setup_lde" in z else None
            ),
            setup_tree=tree,
            selector_paths=vk.selector_paths,
            non_residues=[int(v) for v in z["non_residues"]],
        )
