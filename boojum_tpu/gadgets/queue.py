"""Commitment-chained circuit queues.

Counterpart of `/root/reference/src/gadgets/queue/` (CircuitQueue
`mod.rs:29`, full_state_queue.rs, 1,210 LoC): a FIFO whose contents are
committed by hash chaining — `push` folds the element encoding into the tail
commitment, `pop_front` folds the (witness-provided) element into the head
commitment, and `enforce_consistency` ties the ends together so the popped
sequence must equal the pushed sequence. Length tracking is a range-checked
counter; underflow is impossible because the length after a pop is
re-range-checked.

`CircuitQueue` carries a capacity-sized (4-element) commitment;
`FullStateCircuitQueue` carries the whole width-12 sponge state as the
commitment (cheaper chaining: one permutation per op, no squeeze)."""

from __future__ import annotations

from collections import deque

from ..cs.gates.simple import FmaGate
from ..field import gl
from .boolean import Boolean
from .chunk_utils import decompose_and_check
from .num import Num
from .poseidon2_rf import SW, circuit_hash_leaf, circuit_permutation

T_COMMIT = 4


class CircuitQueue:
    """FIFO with 4-element head/tail commitments (reference mod.rs:29)."""

    def __init__(self, cs, element_width: int):
        zero = cs.zero_var()
        self.cs = cs
        self.element_width = element_width
        self.head = [zero] * T_COMMIT
        self.tail = [zero] * T_COMMIT
        self.length = Num(zero)
        self._witness: deque = deque()

    def push(self, cs, element_vars):
        assert len(element_vars) == self.element_width
        self.tail = circuit_hash_leaf(cs, list(element_vars) + self.tail)
        self.length = self.length.add_constant(cs, 1)
        # UInt32::add_no_overflow parity (reference mod.rs:186)
        decompose_and_check(cs, self.length.var, 32)
        self._witness.append(
            [cs.get_value(v) for v in element_vars]
        )

    def pop_front(self, cs):
        """Allocate the next element from witness, fold it into the head
        chain, decrement+re-range-check the length (underflow guard)."""
        values = self._witness.popleft()
        el = [cs.alloc_variable_with_value(v) for v in values]
        self.head = circuit_hash_leaf(cs, el + self.head)
        self.length = self.length.add_constant(cs, gl.P - 1)
        decompose_and_check(cs, self.length.var, 32)
        return el

    def push_with_optimizer(self, cs, element_vars, execute: Boolean,
                            id: int, optimizer):
        """Conditional push whose chaining permutation is shared through a
        SpongeOptimizer (reference mod.rs:277 push_with_optimizer): the new
        tail/length only take effect under `execute`, and the hash rounds
        become optimizer requests instead of dedicated permutations."""
        from .queue_optimizer import variable_length_hash_with_optimizer

        assert len(element_vars) == self.element_width
        new_tail = variable_length_hash_with_optimizer(
            cs, list(element_vars) + self.tail, id, execute, optimizer
        )
        self.tail = [
            Num.select(cs, execute, Num(a), Num(b)).var
            for a, b in zip(new_tail, self.tail)
        ]
        incremented = self.length.add_constant(cs, 1)
        # range-check the incremented length (the reference uses
        # UInt32::add_no_overflow here, mod.rs:277) — mirrors pop's guard
        decompose_and_check(cs, incremented.var, 32)
        self.length = Num.select(cs, execute, incremented, self.length)
        if execute.get_value(cs):
            self._witness.append([cs.get_value(v) for v in element_vars])

    def pop_with_optimizer(self, cs, execute: Boolean, id: int, optimizer):
        """Conditional pop through the optimizer (reference mod.rs:420)."""
        from .queue_optimizer import variable_length_hash_with_optimizer

        if execute.get_value(cs):
            values = self._witness.popleft()
        else:
            values = [0] * self.element_width
        el = [cs.alloc_variable_with_value(v) for v in values]
        new_head = variable_length_hash_with_optimizer(
            cs, el + self.head, id, execute, optimizer
        )
        self.head = [
            Num.select(cs, execute, Num(a), Num(b)).var
            for a, b in zip(new_head, self.head)
        ]
        decremented = self.length.add_constant(cs, gl.P - 1)
        self.length = Num.select(cs, execute, decremented, self.length)
        decompose_and_check(cs, self.length.var, 32)
        return el

    def is_empty(self, cs) -> Boolean:
        return self.length.is_zero(cs)

    def enforce_consistency(self, cs):
        """If the queue is (claimed) fully drained, head must equal tail —
        i.e. the popped sequence is exactly the pushed sequence (reference
        mod.rs:506)."""
        empty = self.is_empty(cs)
        for h, t in zip(self.head, self.tail):
            diff = FmaGate.fma(cs, cs.one_var(), t, h, gl.P - 1, 1)
            FmaGate.enforce_fma(
                cs, empty.var, diff, cs.zero_var(), cs.zero_var(), 1, 0
            )

    def enforce_trivial_head(self, cs):
        zero = cs.zero_var()
        for h in self.head:
            FmaGate.enforce_fma(
                cs, cs.one_var(), h, zero, zero, 1, 0
            )


class FullStateCircuitQueue:
    """FIFO carrying the full width-12 state as commitment (reference
    full_state_queue.rs): chaining is a single permutation with the element
    encoding overwriting the rate."""

    def __init__(self, cs, element_width: int):
        assert element_width <= 8, "encoding must fit the sponge rate"
        zero = cs.zero_var()
        self.cs = cs
        self.element_width = element_width
        self.head = [zero] * SW
        self.tail = [zero] * SW
        self.length = Num(zero)
        self._witness: deque = deque()

    def _chain(self, cs, state, element_vars):
        zero = cs.zero_var()
        rate = list(element_vars) + [zero] * (8 - self.element_width)
        return circuit_permutation(cs, rate + state[8:])

    def push(self, cs, element_vars):
        assert len(element_vars) == self.element_width
        self.tail = self._chain(cs, self.tail, element_vars)
        self.length = self.length.add_constant(cs, 1)
        self._witness.append([cs.get_value(v) for v in element_vars])

    def pop_front(self, cs):
        values = self._witness.popleft()
        el = [cs.alloc_variable_with_value(v) for v in values]
        self.head = self._chain(cs, self.head, el)
        self.length = self.length.add_constant(cs, gl.P - 1)
        decompose_and_check(cs, self.length.var, 32)
        return el

    def is_empty(self, cs) -> Boolean:
        return self.length.is_zero(cs)

    def enforce_consistency(self, cs):
        empty = self.is_empty(cs)
        for h, t in zip(self.head, self.tail):
            diff = FmaGate.fma(cs, cs.one_var(), t, h, gl.P - 1, 1)
            FmaGate.enforce_fma(
                cs, empty.var, diff, cs.zero_var(), cs.zero_var(), 1, 0
            )
