"""In-circuit Fiat–Shamir transcript.

Counterpart of `/root/reference/src/gadgets/recursion/recursive_transcript.rs`:
the same sponge algorithm as the host `Poseidon2Transcript`
(`boojum_tpu.transcript`) — overwrite absorption, rescue-prime padding with a
trailing 1 — but over circuit variables via the flattened Poseidon2 gate, so
the recursion circuit recomputes exactly the challenges the prover drew.

Query-index bits mirror the host `BitSource`: each challenge is decomposed
into 64 boolean bits with a canonicity constraint (the high 32 bits all-ones
forces the low 32 bits to zero — Goldilocks p = 2^64 - 2^32 + 1 makes that
the only non-canonical encoding), and only the low `64 - max_needed` bits of
each challenge are consumed.
"""

from __future__ import annotations

from ...cs.gates.simple import BooleanConstraintGate, FmaGate, ReductionGate
from ...field import gl
from ..poseidon2_rf import circuit_permutation


class CircuitTranscript:
    def __init__(self, cs, permutation=None):
        """`permutation` selects the in-circuit round function — the
        Poseidon2 flattened gate by default, or the legacy-Poseidon one
        (`gadgets.poseidon_rf.circuit_permutation`) for proofs drawn with
        `ProofConfig(transcript="poseidon")` (reference
        recursive_transcript.rs is generic over the round function the same
        way)."""
        self.cs = cs
        self._perm = permutation or circuit_permutation
        zero = cs.zero_var()
        self.state = [zero] * 12
        self.buffer: list = []
        self.available: list = []

    def witness_field_elements(self, variables):
        self.buffer.extend(variables)

    def witness_merkle_tree_cap(self, cap_digest_vars):
        for digest in cap_digest_vars:
            self.witness_field_elements(list(digest))

    def get_challenge(self):
        if not self.buffer:
            if self.available:
                return self.available.pop(0)
            self.state = self._perm(self.cs, self.state)
            self.available = list(self.state[:8])
            return self.available.pop(0)
        to_absorb = self.buffer + [self.cs.one_var()]
        self.buffer = []
        zero = self.cs.zero_var()
        while len(to_absorb) % 8 != 0:
            to_absorb.append(zero)
        for i in range(0, len(to_absorb), 8):
            self.state = self._perm(
                self.cs, to_absorb[i : i + 8] + self.state[8:]
            )
        self.available = list(self.state[:8])
        return self.available.pop(0)

    def get_multiple_challenges(self, n: int):
        return [self.get_challenge() for _ in range(n)]

    def get_ext_challenge(self):
        return (self.get_challenge(), self.get_challenge())


def decompose_challenge_canonical(cs, c_var):
    """64 LE boolean bit variables of a challenge with the canonical-repr
    constraint. Returns the bit list."""
    bits = cs.alloc_multiple_variables_without_values(64)

    def resolve(vals):
        x = vals[0]
        return [(x >> i) & 1 for i in range(64)]

    cs.set_values_with_dependencies([c_var], bits, resolve)
    for b in bits:
        BooleanConstraintGate.enforce(cs, b)
    # recomposition: sum b_i 2^i = c (mod p)
    from ..chunk_utils import enforce_chunk_recomposition

    enforce_chunk_recomposition(cs, bits, c_var, bits_per_chunk=1)
    # canonicity: AND(high 32 bits) * (low 32 bits recomposed) == 0
    high_and = bits[32]
    for b in bits[33:]:
        high_and = FmaGate.fma(cs, high_and, b, cs.zero_var(), 1, 0)
    low_acc = None
    shift = 0
    rem = list(bits[:32])
    while rem:
        chunk, rem = rem[:3], rem[3:]
        vars4, cf = [], []
        if low_acc is not None:
            vars4.append(low_acc)
            cf.append(1)
        for b in chunk:
            vars4.append(b)
            cf.append(1 << shift)
            shift += 1
        while len(vars4) < 4:
            vars4.append(cs.zero_var())
            cf.append(0)
        low_acc = ReductionGate.reduce(cs, vars4, cf)
    FmaGate.enforce_fma(cs, high_and, low_acc, cs.zero_var(), cs.zero_var(), 1, 0)
    return bits


class CircuitBitSource:
    """In-circuit face of the host BitSource (`transcript.py:56`): boolean
    bit variables drawn from canonical challenge decompositions."""

    def __init__(self, cs, max_needed_bits: int):
        assert 0 < max_needed_bits < 64
        self.cs = cs
        self.bits: list = []
        self.max_needed = max_needed_bits

    def get_bits(self, transcript: CircuitTranscript, num_bits: int):
        while len(self.bits) < num_bits:
            c = transcript.get_challenge()
            all_bits = decompose_challenge_canonical(self.cs, c)
            usable = 64 - self.max_needed
            self.bits.extend(all_bits[:usable])
        out, self.bits = self.bits[:num_bits], self.bits[num_bits:]
        return out

    def get_index_bits(self, transcript: CircuitTranscript, num_bits: int):
        """LE boolean bit vars of one query index."""
        return self.get_bits(transcript, num_bits)
