"""The recursive verifier: a Boojum proof verified inside a circuit.

Counterpart of `/root/reference/src/gadgets/recursion/recursive_verifier.rs:380`
(`RecursiveVerifier::verify`). Mirrors the host verifier
(`boojum_tpu.prover.verifier.verify`) step for step — transcript replay,
quotient reconstruction at z by running the inner circuit's own gate
evaluators over `CircuitExtOps`, copy-permutation and lookup relations,
DEEP recomputation, Merkle path checks and the FRI fold chain — but every
field op is a gadget constraint and every hash is a flattened-Poseidon2-gate
sponge. Validity is ENFORCED (the witness cannot satisfy the circuit unless
the proof verifies) rather than returned as a Boolean; structural/shape
checks are host-side asserts at synthesis time since they depend only on the
(host-known) VK. This is the one deliberate deviation from the reference,
which returns an `(is_valid, public_inputs)` pair.

Returns (inner_public_input_vars, setup_cap_vars) for the caller to expose.
"""

from __future__ import annotations

from ...field import gl
from ...prover.setup import non_residues_for_copy_permutation
from ...prover.stages import chunk_columns
from ...prover.verifier import _ZRowView, _brev
from ...cs.gates.base import TermsCollector
from ...cs.gates.simple import ConditionalSwapGate, FmaGate
from ..field_like_circuit import CircuitExtOps, CircuitOps
from ..poseidon2_rf import circuit_hash_leaf, circuit_hash_node
from .allocated_proof import AllocatedProof, AllocatedVerificationKey
from .transcript import (
    CircuitBitSource,
    CircuitTranscript,
    decompose_challenge_canonical,
)

INV2 = (gl.P + 1) // 2


def _ext_from_pair(ops, a, b):
    """Opening value of an ext-coefficient poly from its two base-poly
    openings: a + b·w (w = sqrt(7))."""
    w = (ops.cs.zero_var(), ops.cs.one_var())
    return ops.add(a, ops.mul(b, w))


class _PowIter:
    def __init__(self, ops, base):
        self.ops = ops
        self.base = base
        self.cur = ops.one()

    def __next__(self):
        out = self.cur
        self.cur = self.ops.mul(self.cur, self.base)
        return out


def _mux_digest(bops: CircuitOps, bits, digests):
    """Select digests[index] with LE index bit variables (tree of selects)."""
    values = list(digests)
    for b in bits:
        assert len(values) % 2 == 0
        values = [
            [
                bops.select(b, values[2 * i + 1][e], values[2 * i][e])
                for e in range(4)
            ]
            for i in range(len(values) // 2)
        ]
    assert len(values) == 1
    return values[0]


def _verify_merkle_path(cs, bops, leaf_vars, path, cap, idx_bits):
    """Enforce that leaf_vars opens against the cap at the index encoded by
    idx_bits (LE). Mirrors host verify_proof_over_cap (merkle.py:61)."""
    digest = circuit_hash_leaf(cs, leaf_vars)
    for level, sib in enumerate(path):
        bit = idx_bits[level]
        left, right = [], []
        for e in range(4):
            l_e, r_e = ConditionalSwapGate.swap(cs, bit, digest[e], sib[e])
            left.append(l_e)
            right.append(r_e)
        digest = circuit_hash_node(cs, left, right)
    cap_bits = idx_bits[len(path) :]
    assert len(cap) == 1 << len(cap_bits)
    expected = _mux_digest(bops, cap_bits, cap)
    for e in range(4):
        bops.enforce_equal(digest[e], expected[e])


def _point_from_bits(bops: CircuitOps, bits_nat_high_to_low, omega: int, shift: int):
    """g·ω^nat where nat's bits are given bit-reversed: bits list is LE index
    bits; nat bit (m-1-j) = idx bit j. Computed as shift·Π_j
    select(idx_j, ω^{2^{m-1-j}}, 1)."""
    m = len(bits_nat_high_to_low)
    acc = bops.constant(shift)
    for j, bit in enumerate(bits_nat_high_to_low):
        w_pow = gl.pow_(omega, 1 << (m - 1 - j))
        factor = bops.select(bit, bops.constant(w_pow), bops.one())
        acc = bops.mul(acc, factor)
    return acc


def recursive_verify(cs, vk, proof, gates):
    """Synthesize the verification of `proof` (host object) against `vk`
    into `cs`. `gates` is the inner circuit's gate list (the verifier is
    built from the same gate configuration, reference
    recursive_verifier_builder.rs)."""
    ap = AllocatedProof(cs, proof)
    avk = AllocatedVerificationKey(cs, vk)
    ops = CircuitExtOps(cs)
    bops = CircuitOps(cs)

    geometry = vk.geometry
    n = vk.trace_len
    log_n = n.bit_length() - 1
    L = vk.fri_lde_factor
    Q = vk.effective_quotient_degree()
    log_full = log_n + (L.bit_length() - 1)
    Ct = vk.num_copy_cols
    Cg = geometry.num_columns_under_copy_permutation
    W = vk.num_wit_cols
    lp = vk.lookup_params
    lookups = lp is not None and lp.is_enabled
    transcript_kind = getattr(vk, "transcript", "poseidon2")
    assert transcript_kind in ("poseidon2", "poseidon"), (
        "the in-circuit verifier replays algebraic transcripts only "
        "(Poseidon2 or legacy Poseidon — byte transcripts are not "
        "circuit-replayable, matching the reference's recursion-compatible "
        "configurations)"
    )
    if transcript_kind == "poseidon":
        from ..poseidon_rf import circuit_permutation as transcript_perm
    else:
        from ..poseidon2_rf import circuit_permutation as transcript_perm
    lk_specialized = lookups and lp.use_specialized_columns
    M = 1 if lookups else 0
    wdt = lp.width if lookups else 0
    if lk_specialized:
        R = lp.num_repetitions
    elif lookups:
        R = Cg // wdt  # general mode: sub-arguments tile general columns
    else:
        R = 0
    K = geometry.num_constant_columns + (1 if lk_specialized else 0)
    TW = (wdt + 1) if lookups else 0
    assert Ct == (Cg + R * wdt if lk_specialized else Cg)
    assert [g.name for g in gates] == list(vk.gate_names)
    assert len(proof.public_inputs) == len(vk.public_input_locations)

    num_chunks = len(chunk_columns(Ct, geometry.max_allowed_constraint_degree))
    S = 2 * (1 + (num_chunks - 1)) + 2 * R + 2 * M
    B = (Ct + W + M) + (Ct + K + TW) + S + 2 * Q
    assert len(proof.values_at_z) == B and len(proof.values_at_z_omega) == 2
    assert len(proof.values_at_0) == R + M

    # ---- transcript replay ------------------------------------------------
    t = CircuitTranscript(cs, permutation=transcript_perm)
    t.witness_merkle_tree_cap(avk.setup_merkle_cap)
    t.witness_field_elements(ap.public_inputs)
    t.witness_merkle_tree_cap(ap.witness_cap)
    beta = t.get_ext_challenge()
    gamma = t.get_ext_challenge()
    if lookups:
        lookup_beta = t.get_ext_challenge()
        lookup_gamma = t.get_ext_challenge()
    t.witness_merkle_tree_cap(ap.stage2_cap)
    alpha = t.get_ext_challenge()
    t.witness_merkle_tree_cap(ap.quotient_cap)
    z_chal = t.get_ext_challenge()
    for v in ap.values_at_z:
        t.witness_field_elements(list(v))
    for v in ap.values_at_z_omega:
        t.witness_field_elements(list(v))
    for v in ap.values_at_0:
        t.witness_field_elements(list(v))
    deep_ch = t.get_ext_challenge()
    from ...prover.fri import fold_schedule

    schedule = fold_schedule(
        n, vk.fri_final_degree, getattr(vk, "fri_folding_schedule", None)
    )
    num_folds = sum(schedule)
    assert len(proof.fri_caps) == len(schedule)
    fri_challenges = []
    for r in range(len(schedule)):
        t.witness_merkle_tree_cap(ap.fri_caps[r])
        fri_challenges.append(t.get_ext_challenge())
    assert len(proof.final_fri_monomials) == (n >> num_folds)
    for c0, c1 in ap.final_fri_monomials:
        t.witness_field_elements([c0, c1])

    # ---- split openings ---------------------------------------------------
    vals = ap.values_at_z
    wit_vals = vals[: Ct + W + M]
    sigma_vals = vals[Ct + W + M : 2 * Ct + W + M]
    const_vals = vals[2 * Ct + W + M : 2 * Ct + W + M + K]
    table_vals = vals[2 * Ct + W + M + K : 2 * Ct + W + M + K + TW]
    s2_vals = vals[2 * Ct + W + M + K + TW : 2 * Ct + W + M + K + TW + S]
    q_vals = vals[2 * Ct + W + M + K + TW + S :]

    # ---- quotient identity at z ------------------------------------------
    alpha_pows = _PowIter(ops, alpha)
    total = ops.zero()
    for gid, gate in enumerate(gates):
        if gate.num_terms == 0:
            continue
        path = vk.selector_paths[gid]
        sel = ops.one()
        for b, bit in enumerate(path):
            cb = const_vals[b]
            sel = ops.mul(sel, cb if bit else ops.sub(ops.one(), cb))
        reps = gate.num_repetitions(geometry)
        gate_acc = ops.zero()
        for inst in range(reps):
            row = _ZRowView(
                wit_vals, const_vals, inst * gate.principal_width,
                inst * gate.witness_width, len(path), Ct,
            )
            dst = TermsCollector()
            gate.evaluate(ops, row, dst)
            assert len(dst.terms) == gate.num_terms
            for term in dst.terms:
                gate_acc = ops.add(
                    gate_acc, ops.mul(term, next(alpha_pows))
                )
        total = ops.add(total, ops.mul(sel, gate_acc))

    # copy-permutation terms at z
    z_at_z = _ext_from_pair(ops, s2_vals[0], s2_vals[1])
    z_at_zw = _ext_from_pair(ops, ap.values_at_z_omega[0], ap.values_at_z_omega[1])
    partial_at_z = [
        _ext_from_pair(ops, s2_vals[2 + 2 * j], s2_vals[3 + 2 * j])
        for j in range(num_chunks - 1)
    ]
    non_residues = non_residues_for_copy_permutation(Ct)
    chunks = chunk_columns(Ct, geometry.max_allowed_constraint_degree)
    z_pow_n = ops.pow(z_chal, n)
    zh_at_z = ops.sub(z_pow_n, ops.one())
    l0_at_z = ops.mul(
        ops.mul_by_base_constant(zh_at_z, gl.inv(n)),
        ops.inv(ops.sub(z_chal, ops.one())),
    )
    term = ops.mul(l0_at_z, ops.sub(z_at_z, ops.one()))
    total = ops.add(total, ops.mul(term, next(alpha_pows)))
    lhs_seq = partial_at_z + [z_at_zw]
    rhs_seq = [z_at_z] + partial_at_z
    for j, chunk in enumerate(chunks):
        num_p = ops.one()
        den_p = ops.one()
        for col in chunk:
            w = wit_vals[col]
            kx = ops.mul_by_base_constant(z_chal, non_residues[col])
            num = ops.add(ops.add(w, ops.mul(beta, kx)), gamma)
            den = ops.add(
                ops.add(w, ops.mul(beta, sigma_vals[col])), gamma
            )
            num_p = ops.mul(num_p, num)
            den_p = ops.mul(den_p, den)
        rel = ops.sub(
            ops.mul(lhs_seq[j], den_p), ops.mul(rhs_seq[j], num_p)
        )
        total = ops.add(total, ops.mul(rel, next(alpha_pows)))

    # lookup terms at z + the sum check at 0 (both placement families —
    # reference lookup_placement.rs:21 + recursive_verifier.rs:380)
    if lookups:
        ab_off = 2 * (1 + (num_chunks - 1))
        gpow = [ops.one()]
        for _ in range(wdt + 1):
            gpow.append(ops.mul(gpow[-1], lookup_gamma))
        if lk_specialized:
            tid_at_z = const_vals[K - 1]
            a_numerator = ops.one()
            col_base = Cg
        else:
            # general mode: the table id is the marker row's constant and
            # each A relation is gated by the marker's SELECTOR at z
            mk_gid = next(
                (
                    i for i, g in enumerate(gates)
                    if getattr(g, "is_lookup_marker", False)
                ),
                None,
            )
            assert mk_gid is not None, (
                "general-mode VK but no marker gate supplied"
            )
            mk_path = vk.selector_paths[mk_gid]
            tid_at_z = const_vals[len(mk_path)]
            sel_at_z = ops.one()
            for bdx, bit in enumerate(mk_path):
                cb = const_vals[bdx]
                sel_at_z = ops.mul(
                    sel_at_z, cb if bit else ops.sub(ops.one(), cb)
                )
            a_numerator = sel_at_z
            col_base = 0
        for i in range(R):
            a_i = _ext_from_pair(
                ops, s2_vals[ab_off + 2 * i], s2_vals[ab_off + 2 * i + 1]
            )
            den = lookup_beta
            for j in range(wdt):
                wv = wit_vals[col_base + i * wdt + j]
                den = ops.add(den, ops.mul(gpow[j], wv))
            den = ops.add(den, ops.mul(gpow[wdt], tid_at_z))
            rel = ops.sub(ops.mul(a_i, den), a_numerator)
            total = ops.add(total, ops.mul(rel, next(alpha_pows)))
        b_at_z = _ext_from_pair(
            ops, s2_vals[ab_off + 2 * R], s2_vals[ab_off + 2 * R + 1]
        )
        den = lookup_beta
        for j in range(wdt + 1):
            den = ops.add(den, ops.mul(gpow[j], table_vals[j]))
        m_at_z = wit_vals[Ct + W]
        rel = ops.sub(ops.mul(b_at_z, den), m_at_z)
        total = ops.add(total, ops.mul(rel, next(alpha_pows)))
        a_sum = ops.zero()
        for i in range(R):
            a_sum = ops.add(a_sum, ap.values_at_0[i])
        ops.enforce_equal(a_sum, ap.values_at_0[R])

    # T(z)·Z_H(z) == total
    t_at_z = ops.zero()
    z_pows = _PowIter(ops, z_pow_n)
    for i in range(Q):
        q_i = _ext_from_pair(ops, q_vals[2 * i], q_vals[2 * i + 1])
        t_at_z = ops.add(t_at_z, ops.mul(q_i, next(z_pows)))
    ops.enforce_equal(total, ops.mul(t_at_z, zh_at_z))

    # ---- PoW --------------------------------------------------------------
    if vk.pow_bits > 0:
        seed = t.get_multiple_challenges(4)
        h = circuit_hash_leaf(cs, seed + [ap.pow_challenge])
        h_bits = decompose_challenge_canonical(cs, h[0])
        for b in h_bits[: vk.pow_bits]:
            FmaGate.enforce_fma(
                cs, cs.one_var(), b, cs.zero_var(), cs.zero_var(), 1, 0
            )
        t.witness_field_elements([ap.pow_challenge])

    # ---- queries ----------------------------------------------------------
    assert len(proof.queries) == vk.num_queries
    omega = gl.omega(log_n)
    zw = ops.mul_by_base_constant(z_chal, omega)
    pi_locs = vk.public_input_locations
    bs = CircuitBitSource(cs, log_full)
    omega_full = gl.omega(log_full)
    g = gl.MULTIPLICATIVE_GENERATOR
    for q in ap.queries:
        idx_bits = bs.get_index_bits(t, log_full)
        _verify_merkle_path(
            cs, bops, q.witness.leaf_values, q.witness.path, ap.witness_cap,
            idx_bits,
        )
        _verify_merkle_path(
            cs, bops, q.stage2.leaf_values, q.stage2.path, ap.stage2_cap,
            idx_bits,
        )
        _verify_merkle_path(
            cs, bops, q.quotient.leaf_values, q.quotient.path,
            ap.quotient_cap, idx_bits,
        )
        _verify_merkle_path(
            cs, bops, q.setup.leaf_values, q.setup.path,
            avk.setup_merkle_cap, idx_bits,
        )
        assert len(q.witness.leaf_values) == Ct + W + M
        assert len(q.setup.leaf_values) == Ct + K + TW
        assert len(q.stage2.leaf_values) == S
        assert len(q.quotient.leaf_values) == 2 * Q

        # x = g·ω^brev(idx): nat bit (log-1-j) = idx bit j
        x = _point_from_bits(bops, idx_bits, omega_full, g)
        f_all = (
            [ops.from_base_var(v) for v in q.witness.leaf_values]
            + [ops.from_base_var(v) for v in q.setup.leaf_values]
            + [ops.from_base_var(v) for v in q.stage2.leaf_values]
            + [ops.from_base_var(v) for v in q.quotient.leaf_values]
        )
        inv_xz = ops.inv(ops.sub(ops.from_base_var(x), z_chal))
        inv_xzw = ops.inv(ops.sub(ops.from_base_var(x), zw))
        h_val = ops.zero()
        ch_iter = _PowIter(ops, deep_ch)
        for i in range(B):
            diff = ops.sub(f_all[i], vals[i])
            h_val = ops.add(
                h_val, ops.mul(ops.mul(diff, inv_xz), next(ch_iter))
            )
        for i in range(2):
            f = ops.from_base_var(q.stage2.leaf_values[i])
            diff = ops.sub(f, ap.values_at_z_omega[i])
            h_val = ops.add(
                h_val, ops.mul(ops.mul(diff, inv_xzw), next(ch_iter))
            )
        if lookups:
            inv_x = bops.inv(x)
            ab_off = 2 * (1 + (num_chunks - 1))
            for i in range(R + 1):
                ch = next(ch_iter)
                f_pair = (
                    q.stage2.leaf_values[ab_off + 2 * i],
                    q.stage2.leaf_values[ab_off + 2 * i + 1],
                )
                diff = ops.sub(f_pair, ap.values_at_0[i])
                h_val = ops.add(
                    h_val, ops.mul(ops.mul_by_base(diff, inv_x), ch)
                )
        for k_pi, (col, row) in enumerate(pi_locs):
            ch = next(ch_iter)
            pt = gl.pow_(omega, row)
            diff = bops.sub(
                q.witness.leaf_values[col], ap.public_inputs[k_pi]
            )
            denom = bops.inv(
                FmaGate.fma(cs, bops.one(), x, cs.allocate_constant(pt),
                            1, gl.P - 1)
            )
            tb = bops.mul(diff, denom)
            h_val = ops.add(h_val, ops.mul_by_base(ch, tb))

        # FRI chain (grouped oracles per the folding schedule): each leaf
        # carries a whole 2^k fold subtree; the circuit folds the entire
        # leaf with sub-challenges ch, ch^2, ... (reference fri/mod.rs:362)
        assert len(q.fri) == len(schedule)
        cur_expected = None
        off = 0
        for r, (k_r, oq) in enumerate(zip(schedule, q.fri)):
            block = 1 << k_r
            assert len(oq.leaf_values) == 2 * block
            leaf_idx_bits = idx_bits[off + k_r :]
            _verify_merkle_path(
                cs, bops, oq.leaf_values, oq.path, ap.fri_caps[r],
                leaf_idx_bits,
            )
            points = [
                (oq.leaf_values[2 * j], oq.leaf_values[2 * j + 1])
                for j in range(block)
            ]
            # the value this query tracks = points muxed by the in-block bits
            sel_vals = list(points)
            for b in idx_bits[off : off + k_r]:
                sel_vals = [
                    ops.select(b, sel_vals[2 * i + 1], sel_vals[2 * i])
                    for i in range(len(sel_vals) // 2)
                ]
            mine = sel_vals[0]
            if cur_expected is None:
                ops.enforce_equal(mine, h_val)
            else:
                ops.enforce_equal(mine, cur_expected)
            # fold the whole leaf down k_r times
            dbits = idx_bits[off + k_r : log_full]
            fold_vals = points
            ch = fri_challenges[r]
            for j in range(k_r):
                fr = off + j
                log_nr = log_full - fr
                omega_r = gl.pow_(omega_full, 1 << fr)
                shift_r = gl.pow_(g, 1 << fr)
                # the dbits product is invariant in m: synthesize it once
                # per sub-fold, then scale by the per-m host constant
                base_point = _point_from_bits(bops, dbits, omega_r, 1)
                nxt = []
                for m in range(len(fold_vals) // 2):
                    # even element's global index: low bit 0, then the
                    # STATIC bits of m, then the leaf index bits
                    static_nat = 0
                    for tbit in range(k_r - j - 1):
                        if (m >> tbit) & 1:
                            static_nat += 1 << (log_nr - 2 - tbit)
                    shift_eff = gl.mul(
                        shift_r, gl.pow_(omega_r, static_nat)
                    )
                    x_r = bops.mul(base_point, bops.constant(shift_eff))
                    even, odd = fold_vals[2 * m], fold_vals[2 * m + 1]
                    s = ops.add(even, odd)
                    d = ops.sub(even, odd)
                    dox = ops.mul_by_base(d, bops.inv(x_r))
                    folded = ops.add(s, ops.mul(dox, ch))
                    nxt.append(ops.mul_by_base_constant(folded, INV2))
                fold_vals = nxt
                ch = ops.mul(ch, ch)
            cur_expected = fold_vals[0]
            off += k_r

        # final monomial evaluation at the fully folded point
        log_fin = log_full - num_folds
        fin_bits = idx_bits[num_folds : num_folds + log_fin]
        shift_fin = gl.pow_(g, 1 << num_folds)
        x_fin = _point_from_bits(bops, fin_bits, gl.omega(log_fin), shift_fin)
        acc = ops.zero()
        xp = ops.one()
        for c in ap.final_fri_monomials:
            acc = ops.add(acc, ops.mul(c, xp))
            xp = ops.mul_by_base(xp, x_fin)
        ops.enforce_equal(acc, cur_expected)

    return ap.public_inputs, avk.setup_merkle_cap
