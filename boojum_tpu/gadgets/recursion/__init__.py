"""Recursive verification layer (reference `/root/reference/src/gadgets/recursion/`):
a Boojum verifier expressed as a circuit, so one proof attests to another.
"""

from .transcript import CircuitTranscript, CircuitBitSource
from .allocated_proof import AllocatedProof, AllocatedVerificationKey
from .verifier import recursive_verify

__all__ = [
    "CircuitTranscript",
    "CircuitBitSource",
    "AllocatedProof",
    "AllocatedVerificationKey",
    "recursive_verify",
]
