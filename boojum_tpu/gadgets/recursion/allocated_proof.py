"""Proof and verification key as circuit witnesses.

Counterpart of `/root/reference/src/gadgets/recursion/allocated_proof.rs` and
`allocated_vk.rs`: every field element of a host `Proof` /`VerificationKey`
becomes an allocated variable; the fixed parameters (geometry, FRI schedule,
gate set) stay host-side — they shape the circuit, they are not witness data.
"""

from __future__ import annotations

from ...field import gl


def _alloc(cs, v: int) -> int:
    return cs.alloc_variable_with_value(int(v) % gl.P)


def _alloc_cap(cs, cap):
    return [[_alloc(cs, x) for x in digest] for digest in cap]


def _alloc_pairs(cs, pairs):
    return [(_alloc(cs, c0), _alloc(cs, c1)) for (c0, c1) in pairs]


class AllocatedOracleQuery:
    def __init__(self, cs, query):
        self.leaf_values = [_alloc(cs, v) for v in query.leaf_values]
        self.path = [[_alloc(cs, x) for x in sib] for sib in query.path]


class AllocatedSingleRoundQueries:
    def __init__(self, cs, q):
        self.witness = AllocatedOracleQuery(cs, q.witness)
        self.stage2 = AllocatedOracleQuery(cs, q.stage2)
        self.quotient = AllocatedOracleQuery(cs, q.quotient)
        self.setup = AllocatedOracleQuery(cs, q.setup)
        self.fri = [AllocatedOracleQuery(cs, f) for f in q.fri]


class AllocatedProof:
    """Witness allocation of a host Proof (reference allocated_proof.rs)."""

    def __init__(self, cs, proof):
        self.public_inputs = [_alloc(cs, v) for v in proof.public_inputs]
        self.witness_cap = _alloc_cap(cs, proof.witness_cap)
        self.stage2_cap = _alloc_cap(cs, proof.stage2_cap)
        self.quotient_cap = _alloc_cap(cs, proof.quotient_cap)
        self.values_at_z = _alloc_pairs(cs, proof.values_at_z)
        self.values_at_z_omega = _alloc_pairs(cs, proof.values_at_z_omega)
        self.values_at_0 = _alloc_pairs(cs, proof.values_at_0)
        self.fri_caps = [_alloc_cap(cs, c) for c in proof.fri_caps]
        self.final_fri_monomials = _alloc_pairs(cs, proof.final_fri_monomials)
        self.queries = [
            AllocatedSingleRoundQueries(cs, q) for q in proof.queries
        ]
        self.pow_challenge = _alloc(cs, proof.pow_challenge)


class AllocatedVerificationKey:
    """Witness allocation of the VK's setup cap; the structural fields stay
    host-side on the vk object (reference allocated_vk.rs)."""

    def __init__(self, cs, vk):
        self.setup_merkle_cap = _alloc_cap(cs, vk.setup_merkle_cap)
        self.vk = vk
