"""Wide unsigned integers as u32-limb vectors: UInt160 / UInt256 / UInt512.

Counterpart of `/root/reference/src/gadgets/u160,u256,u512/` (3,249 LoC with
u8/u16/u32): checked arithmetic with carry chains over the U32 gates,
widening multiplication (schoolbook over u32 limbs through the U32 FMA gate),
byte (de)compositions, masking and equality. Limb range correctness comes
from the 4-bit-chunk lookups (`decompose_and_check`), carry relations from
the dedicated u32 gates — the same split the reference uses.
"""

from __future__ import annotations

from ..cs.gates.simple import ReductionGate
from ..cs.gates.u32 import U32AddGate, U32FmaGate, U32SubGate
from .boolean import Boolean
from .chunk_utils import decompose_and_check
from .num import Num
from .uint import UInt8, UInt32


class UIntWide:
    """Base: little-endian vector of NUM_LIMBS UInt32 limbs."""

    NUM_LIMBS = 0
    __slots__ = ("limbs",)

    def __init__(self, limbs):
        assert len(limbs) == self.NUM_LIMBS
        self.limbs = list(limbs)

    @property
    def BITS(self):
        return 32 * self.NUM_LIMBS

    # -- allocation ---------------------------------------------------------

    @classmethod
    def allocate_checked(cls, cs, value: int):
        assert 0 <= value < (1 << (32 * cls.NUM_LIMBS))
        limbs = [
            UInt32.allocate_checked(cs, (value >> (32 * i)) & 0xFFFFFFFF)
            for i in range(cls.NUM_LIMBS)
        ]
        return cls(limbs)

    @classmethod
    def allocated_constant(cls, cs, value: int):
        assert 0 <= value < (1 << (32 * cls.NUM_LIMBS))
        limbs = [
            UInt32.allocated_constant(cs, (value >> (32 * i)) & 0xFFFFFFFF)
            for i in range(cls.NUM_LIMBS)
        ]
        return cls(limbs)

    @classmethod
    def zero(cls, cs):
        return cls.allocated_constant(cs, 0)

    def get_value(self, cs) -> int:
        out = 0
        for i, limb in enumerate(self.limbs):
            out |= limb.get_value(cs) << (32 * i)
        return out

    # -- arithmetic ---------------------------------------------------------

    def overflowing_add(self, cs, other):
        """(self + other mod 2^BITS, overflow Boolean) — u32 carry chain
        (reference u256/mod.rs:166)."""
        assert type(self) is type(other)
        carry = cs.zero_var()
        out = []
        for a, b in zip(self.limbs, other.limbs):
            c, carry = U32AddGate.add(cs, a.var, b.var, carry)
            decompose_and_check(cs, c, 32)
            out.append(UInt32(c))
        return type(self)(out), Boolean(carry)

    def overflowing_sub(self, cs, other):
        """(self - other mod 2^BITS, borrow Boolean) (reference :188)."""
        assert type(self) is type(other)
        borrow = cs.zero_var()
        out = []
        for a, b in zip(self.limbs, other.limbs):
            c, borrow = U32SubGate.sub(cs, a.var, b.var, borrow)
            decompose_and_check(cs, c, 32)
            out.append(UInt32(c))
        return type(self)(out), Boolean(borrow)

    # -- predicates / control ----------------------------------------------

    def is_zero(self, cs) -> Boolean:
        """Σ limbs == 0 (limbs are nonneg and the sum stays far below p)."""
        total = Num.linear_combination(
            cs, [limb.into_num() for limb in self.limbs],
            [1] * self.NUM_LIMBS,
        )
        return total.is_zero(cs)

    @staticmethod
    def equals(cs, a, b) -> Boolean:
        assert type(a) is type(b)
        diff, borrow = a.overflowing_sub(cs, b)
        return diff.is_zero(cs).and_(cs, borrow.negate(cs))

    def mask(self, cs, flag: Boolean):
        """flag ? self : 0 (reference :252)."""
        zero = cs.zero_var()
        out = [
            UInt32(Num(limb.var).mask(cs, flag).var) for limb in self.limbs
        ]
        return type(self)(out)

    def mask_negated(self, cs, flag: Boolean):
        return self.mask(cs, flag.negate(cs))

    @staticmethod
    def select(cs, flag: Boolean, a, b):
        assert type(a) is type(b)
        out = [
            UInt32.select(cs, flag, la, lb)
            for la, lb in zip(a.limbs, b.limbs)
        ]
        return type(a)(out)

    # -- byte casts ---------------------------------------------------------

    @classmethod
    def from_le_bytes(cls, cs, bytes_le):
        assert len(bytes_le) == 4 * cls.NUM_LIMBS
        limbs = []
        for i in range(cls.NUM_LIMBS):
            b = bytes_le[4 * i : 4 * i + 4]
            v = ReductionGate.reduce(
                cs, [x.var for x in b], [1, 1 << 8, 1 << 16, 1 << 24]
            )
            limbs.append(UInt32(v))
        return cls(limbs)

    @classmethod
    def from_be_bytes(cls, cs, bytes_be):
        return cls.from_le_bytes(cs, list(reversed(bytes_be)))

    def to_le_bytes(self, cs):
        out = []
        for limb in self.limbs:
            out.extend(limb.to_le_bytes(cs))
        return out

    def to_be_bytes(self, cs):
        return list(reversed(self.to_le_bytes(cs)))

    # -- bit structure ------------------------------------------------------

    def div2(self, cs):
        """(self >> 1, low bit Boolean): x = 2·y + b via the u32 add gate
        applied limbwise, top-down (reference u256/mod.rs:333)."""
        n = self.NUM_LIMBS
        ys = cs.alloc_multiple_variables_without_values(n)
        bit = cs.alloc_variable_without_value()

        def resolve(vals):
            x = sum(v << (32 * i) for i, v in enumerate(vals))
            y = x >> 1
            return [(y >> (32 * i)) & 0xFFFFFFFF for i in range(n)] + [x & 1]

        cs.set_values_with_dependencies(
            [limb.var for limb in self.limbs], list(ys) + [bit], resolve
        )
        Boolean.from_variable_checked(cs, bit)
        # carry chain: 2·y_i + c_i = x_i + 2^32·c_{i+1}; c_0 = bit
        carry = bit
        for i in range(n):
            # place the u32 add gate over existing vars: y+y+cin = x + 2^32·cout
            cout = (
                cs.alloc_variable_without_value()
                if i + 1 < n
                else cs.zero_var()
            )
            if i + 1 < n:
                cs.set_values_with_dependencies(
                    [ys[i], carry],
                    [cout],
                    lambda v: [(2 * v[0] + v[1]) >> 32],
                )
            cs.place_gate(
                U32AddGate.instance(),
                [ys[i], ys[i], carry, self.limbs[i].var, cout],
                (),
            )
            decompose_and_check(cs, ys[i], 32)
            carry = cout
        return type(self)([UInt32(y) for y in ys]), Boolean(bit)

    def is_odd(self, cs) -> Boolean:
        return self.div2(cs)[1]


class UInt160(UIntWide):
    NUM_LIMBS = 5


class UInt256(UIntWide):
    NUM_LIMBS = 8

    def widening_mul(self, cs, other: "UInt256") -> "UInt512":
        """Full 512-bit product via schoolbook u32 limbs (reference
        u256/mod.rs:218): row i accumulates a_i·b_j into the running result
        limbs through the u32 FMA gate's (low, high) split."""
        n = self.NUM_LIMBS
        res = [cs.zero_var()] * (2 * n)
        for i in range(n):
            carry = cs.zero_var()
            for j in range(n):
                low, high = U32FmaGate.fma(
                    cs, self.limbs[i].var, other.limbs[j].var,
                    res[i + j], carry,
                )
                decompose_and_check(cs, low, 32)
                decompose_and_check(cs, high, 32)
                res[i + j] = low
                carry = high
            res[i + n] = carry
        return UInt512([UInt32(v) for v in res])


class UInt512(UIntWide):
    NUM_LIMBS = 16

    def to_low(self) -> UInt256:
        return UInt256(self.limbs[:8])

    def to_high(self) -> UInt256:
        return UInt256(self.limbs[8:])
