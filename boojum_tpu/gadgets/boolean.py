"""Boolean gadget (reference `/root/reference/src/gadgets/boolean/`, 715 LoC).

A Boolean wraps a variable constrained to {0,1} via the x²=x gate. Logic ops
are single FMA gates over the arithmetic encodings:
  and: a·b          or: a+b−a·b        xor: a+b−2ab        not: 1−a
"""

from __future__ import annotations

from ..cs.gates.simple import BooleanConstraintGate, FmaGate, SelectionGate
from ..field import gl


class Boolean:
    __slots__ = ("var",)

    def __init__(self, var: int):
        self.var = var

    # -- allocation ---------------------------------------------------------

    @staticmethod
    def allocate(cs, value: bool) -> "Boolean":
        v = cs.alloc_variable_with_value(1 if value else 0)
        BooleanConstraintGate.enforce(cs, v)
        return Boolean(v)

    @staticmethod
    def allocated_constant(cs, value: bool) -> "Boolean":
        return Boolean(cs.one_var() if value else cs.zero_var())

    @staticmethod
    def from_variable_checked(cs, var: int) -> "Boolean":
        BooleanConstraintGate.enforce(cs, var)
        return Boolean(var)

    def get_value(self, cs) -> bool:
        return cs.get_value(self.var) == 1

    # -- logic --------------------------------------------------------------

    def and_(self, cs, other: "Boolean") -> "Boolean":
        return Boolean(FmaGate.fma(cs, self.var, other.var, cs.zero_var(), 1, 0))

    def or_(self, cs, other: "Boolean") -> "Boolean":
        # a + b - ab  =  -(a·b) + 1·(a+b); build via t = a·b, out = a+b-t
        t = FmaGate.fma(cs, self.var, other.var, cs.zero_var(), 1, 0)
        s = FmaGate.fma(cs, cs.one_var(), self.var, other.var, 1, 1)
        return Boolean(FmaGate.fma(cs, cs.one_var(), t, s, gl.P - 1, 1))

    def xor(self, cs, other: "Boolean") -> "Boolean":
        # a + b - 2ab
        s = FmaGate.fma(cs, cs.one_var(), self.var, other.var, 1, 1)
        return Boolean(FmaGate.fma(cs, self.var, other.var, s, gl.P - 2, 1))

    def negate(self, cs) -> "Boolean":
        # 1 - a  =  (P-1)·one·a + 1·one
        return Boolean(
            FmaGate.fma(cs, cs.one_var(), self.var, cs.one_var(), gl.P - 1, 1)
        )

    @staticmethod
    def select(cs, flag: "Boolean", a: "Boolean", b: "Boolean") -> "Boolean":
        return Boolean(SelectionGate.select(cs, flag.var, a.var, b.var))

    @staticmethod
    def multi_and(cs, bools) -> "Boolean":
        acc = bools[0]
        for b in bools[1:]:
            acc = acc.and_(cs, b)
        return acc

    @staticmethod
    def multi_or(cs, bools) -> "Boolean":
        acc = bools[0]
        for b in bools[1:]:
            acc = acc.or_(cs, b)
        return acc
