from .tables import (
    trixor4_table,
    ch4_table,
    maj4_table,
    split4bit_table,
)
from .sha256 import sha256, sha256_digest_bytes, allocate_u8_input
from .keccak256 import keccak256, keccak256_digest_bytes
from .blake2s import blake2s, blake2s_digest_bytes
from .boolean import Boolean
from .num import Num
from .uint import UInt8, UInt16, UInt32
