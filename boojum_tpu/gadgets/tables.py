"""Gadget lookup-table builders.

Counterparts of `/root/reference/src/gadgets/tables/`: trixor4.rs, ch4.rs,
maj4.rs, chunk4bits.rs (and the 8-bit binops / range checks, which live in
`boojum_tpu.cs.lookup_table`). All SHA-256 tables are width ≤ 4 so they fit
the reference bench's width-4 specialized lookup sub-arguments.
"""

from __future__ import annotations

import numpy as np

from ..cs.lookup_table import LookupTable


def _tri_table(name: str, fn) -> LookupTable:
    """All (a, b, c) in [0,16)^3 -> fn(a,b,c) & 0xF; 4096 rows."""
    a = np.arange(16, dtype=np.uint64).repeat(256)
    b = np.tile(np.arange(16, dtype=np.uint64).repeat(16), 16)
    c = np.tile(np.arange(16, dtype=np.uint64), 256)
    v = fn(a, b, c) & np.uint64(0xF)
    return LookupTable(name, 3, 1, np.stack([a, b, c, v], axis=1))


def trixor4_table() -> LookupTable:
    """a ^ b ^ c on 4-bit chunks (reference trixor4.rs). Doubles as the
    4-bit range check (lookup membership forces chunks into [0,16))."""
    return _tri_table("trixor4", lambda a, b, c: a ^ b ^ c)


def ch4_table() -> LookupTable:
    """SHA-256 choice: (a & b) ^ (~a & c) on 4-bit chunks (reference ch4.rs)."""
    return _tri_table("ch4", lambda a, b, c: (a & b) ^ (~a & c))


def maj4_table() -> LookupTable:
    """SHA-256 majority: (a&b) ^ (a&c) ^ (b&c) (reference maj4.rs)."""
    return _tri_table("maj4", lambda a, b, c: (a & b) ^ (a & c) ^ (b & c))


def byte_split_table(split_at: int) -> LookupTable:
    """x in [0,256) -> (low = x mod 2^split_at, high = x >> split_at)
    (reference tables/byte_split.rs). One table per split point; used by the
    bit-rotation gadgets in Keccak-256 and Blake2s."""
    assert 0 < split_at < 8
    x = np.arange(256, dtype=np.uint64)
    low = x & np.uint64((1 << split_at) - 1)
    high = x >> np.uint64(split_at)
    return LookupTable(
        f"byte_split_at{split_at}", 1, 2, np.stack([x, low, high], axis=1)
    )


def split4bit_table(split_at: int) -> LookupTable:
    """x in [0,16) -> (low = x & mask, high = x >> split_at, reversed =
    low·2^(4-split_at) | high) (reference chunk4bits.rs
    create_4bit_chunk_split_table)."""
    assert split_at in (1, 2)
    x = np.arange(16, dtype=np.uint64)
    low = x & np.uint64((1 << split_at) - 1)
    high = x >> np.uint64(split_at)
    rev = (low << np.uint64(4 - split_at)) | high
    return LookupTable(
        f"split4bit_at{split_at}", 1, 3, np.stack([x, low, high, rev], axis=1)
    )
