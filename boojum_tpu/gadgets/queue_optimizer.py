"""Sponge round batching across mutually-exclusive queue operations.

Counterpart of `/root/reference/src/gadgets/queue/queue_optimizer/`
(`sponge_optimizer.rs`, `mod.rs`): circuits that in any given execution step
perform AT MOST ONE of N possible queue operations (the Era main VM's
opcode-dispatched queues) would otherwise pay N permutations per step — one
per possible operation, all but one gated off. The optimizer batches them:
each operation registers a *request* `(initial_state, claimed_final_state,
applies)` under its stream id, and `enforce()` lays down ONE real permutation
per request slot, selecting among the per-slot requests by their (provably
at-most-one-hot) `applies` flags and conditionally enforcing the selected
claimed final state.

The claimed final states are witness-allocated by the absorb helper (zeros
when the operation does not execute), so non-executing branches cost only
selects — the permutation constraints are shared.
"""

from __future__ import annotations

from ..cs.gates.simple import FmaGate, ReductionGate
from ..field import gl
from ..hashes.poseidon2 import poseidon2_permutation_host
from .boolean import Boolean
from .num import Num
from .poseidon2_rf import RATE, SW, circuit_permutation

T_COMMIT = 4


class SpongeOptimizer:
    """Batches sponge-round requests from `num_ids` mutually exclusive
    request streams into at most `capacity` real permutations (reference
    sponge_optimizer.rs `SpongeOptimizer`)."""

    def __init__(self, cs, capacity: int, num_ids: int):
        self.cs = cs
        self.capacity = capacity
        self.num_ids = num_ids
        self.requests: list[list] = [[] for _ in range(num_ids)]

    def add_request(self, initial_state, claimed_final_state,
                    applies: Boolean, id: int):
        assert len(initial_state) == SW and len(claimed_final_state) == SW
        stream = self.requests[id]
        assert len(stream) < self.capacity, (
            f"over capacity: capacity is {self.capacity} but stream {id} "
            f"already has {len(stream)} requests"
        )
        stream.append((list(initial_state), list(claimed_final_state), applies))

    def is_fresh(self) -> bool:
        return all(not s for s in self.requests)

    def enforce(self):
        """One permutation per request slot; per-slot requests are selected
        by their applies flags (enforced at-most-one-hot) and the selected
        claimed state is conditionally enforced."""
        cs = self.cs
        zero = cs.zero_var()
        for slot in range(self.capacity):
            per_slot = [s[slot] for s in self.requests if slot < len(s)]
            if not per_slot:
                continue
            if len(per_slot) == 1:
                init, claimed, applies = per_slot[0]
            else:
                # at-most-one-hot: the sum of flags must itself be boolean —
                # and that checked sum IS the OR of the flags, so it doubles
                # as the combined applies flag for free
                flags = [r[2] for r in per_slot]
                bit_sum = flags[0].var
                for f in flags[1:]:
                    bit_sum = ReductionGate.reduce(
                        cs, [bit_sum, f.var, zero, zero], [1, 1, 0, 0]
                    )
                applies = Boolean.from_variable_checked(cs, bit_sum)
                init, claimed, _ = per_slot[0]
                for nxt_init, nxt_claimed, flag in per_slot[1:]:
                    init = [
                        Num.select(cs, flag, Num(a), Num(b)).var
                        for a, b in zip(nxt_init, init)
                    ]
                    claimed = [
                        Num.select(cs, flag, Num(a), Num(b)).var
                        for a, b in zip(nxt_claimed, claimed)
                    ]
            result = circuit_permutation(cs, init)
            for res, want in zip(result, claimed):
                diff = FmaGate.fma(cs, cs.one_var(), want, res, gl.P - 1, 1)
                FmaGate.enforce_fma(cs, applies.var, diff, zero, zero, 1, 0)
        for s in self.requests:
            s.clear()


def absorb_into_state_with_optimizer(cs, input_vars, into_state, id: int,
                                     execute: Boolean, optimizer):
    """Overwrite-mode absorption of `input_vars` into `into_state` whose
    permutations go through the optimizer (reference mod.rs
    `variable_length_absorb_into_state_using_optimizer`): intermediate
    states are witness-allocated (zeros when not executing) and each round
    becomes one shared request."""
    zero = cs.zero_var()
    chunks = []
    rem = list(input_vars)
    while rem:
        head, rem = rem[:RATE], rem[RATE:]
        chunks.append(head + [zero] * (RATE - len(head)))
    state = list(into_state)
    for chunk in chunks:
        outs = cs.alloc_multiple_variables_without_values(SW)

        def resolve(vals):
            st, absorbed, exe = vals[:SW], vals[SW:SW + RATE], vals[SW + RATE]
            if exe == 0:
                return [0] * SW
            return poseidon2_permutation_host(
                list(absorbed) + list(st[RATE:])
            )

        cs.set_values_with_dependencies(
            state + chunk + [execute.var], outs, resolve
        )
        provably_absorbed = chunk + state[RATE:]
        optimizer.add_request(provably_absorbed, outs, execute, id)
        state = list(outs)
    return state


def variable_length_hash_with_optimizer(cs, input_vars, id: int,
                                        execute: Boolean, optimizer,
                                        n=T_COMMIT):
    """Hash through the optimizer from an empty state; returns the
    `n`-element commitment (reference mod.rs
    `variable_length_hash_using_optimizer`)."""
    zero = cs.zero_var()
    state = absorb_into_state_with_optimizer(
        cs, input_vars, [zero] * SW, id, execute, optimizer
    )
    return state[:n]
