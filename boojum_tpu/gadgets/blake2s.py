"""Blake2s gadget.

Counterpart of `/root/reference/src/gadgets/blake2s/` (mod.rs:36 `blake2s`,
round_function.rs, mixing_function.rs:26 `mixing_function_g`): state words are
little-endian 4-byte-variable words; additions are one chunked tri-add gate
per `+` (carry range-checked by lookup), xors are 8-bit-table lookups, and the
four G rotations are byte relabelings (16, 8) or split/remerge lookups
(12, 7) — exactly the trade structure of the reference.

Fixed-length, keyless hashing (digest 32): h0 is IV0 twisted by the param
block `0x01010020` (reference mod.rs:17 `IV_0_TWIST`).
"""

from __future__ import annotations

from ..cs.gates.u32 import ByteTriAddGate
from .byte_ops import (
    ensure_byte_split,
    ensure_xor8,
    range_check_byte,
    rotate_bytes_right,
    xor_many,
)

BLAKE2S_ROUNDS = 10
BLOCK_SIZE = 64
DIGEST_SIZE = 32

IV = [
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
]

IV_0_TWIST = IV[0] ^ 0x01010000 ^ 32

SIGMAS = [
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
]


def register_blake2s_tables(cs):
    ensure_xor8(cs)
    ensure_byte_split(cs, 4)  # rotr 12 -> rotl 20, rem 4
    ensure_byte_split(cs, 7)  # rotr 7 -> rotl 25, rem 1 -> split at 7


def _const_word(cs, value: int):
    return [cs.allocate_constant((value >> (8 * i)) & 0xFF) for i in range(4)]


def _tri_add(cs, a, b, x):
    """(a + b + x) mod 2^32 on byte words; carry chunk range-checked
    (reference mixing_function.rs:193 `tri_add_as_byte_chunks`)."""
    out, carry = ByteTriAddGate.add(cs, a, b, x)
    range_check_byte(cs, carry)
    return out


def _g(cs, v, idxes, x, y, zero_word):
    """The G mixing function (reference mixing_function.rs:26). Every
    tri-add output byte is subsequently a lookup key in a xor, which is what
    range-checks it — same argument the reference makes."""
    ia, ib, ic, id_ = idxes
    a, b, c, d = v[ia], v[ib], v[ic], v[id_]

    a = _tri_add(cs, a, b, x)
    d = rotate_bytes_right(cs, xor_many(cs, d, a), 16)
    c = _tri_add(cs, c, d, zero_word)
    b = rotate_bytes_right(cs, xor_many(cs, b, c), 12)
    a = _tri_add(cs, a, b, y)
    d = rotate_bytes_right(cs, xor_many(cs, d, a), 8)
    c = _tri_add(cs, c, d, zero_word)
    b = rotate_bytes_right(cs, xor_many(cs, b, c), 7)

    v[ia], v[ib], v[ic], v[id_] = a, b, c, d


def _compression(cs, h, block_words, offset: int, is_last: bool, zero_word):
    """One Blake2s compression (reference round_function.rs
    `blake2s_round_function`, FixedLength control: t/f words are
    compile-time constants)."""
    v = list(h)
    v += [_const_word(cs, IV[i]) for i in range(4)]
    v.append(_const_word(cs, IV[4] ^ (offset & 0xFFFFFFFF)))
    v.append(_const_word(cs, IV[5] ^ (offset >> 32)))
    v.append(_const_word(cs, IV[6] ^ (0xFFFFFFFF if is_last else 0)))
    v.append(_const_word(cs, IV[7]))

    for rnd in range(BLAKE2S_ROUNDS):
        s = SIGMAS[rnd]
        _g(cs, v, (0, 4, 8, 12), block_words[s[0]], block_words[s[1]], zero_word)
        _g(cs, v, (1, 5, 9, 13), block_words[s[2]], block_words[s[3]], zero_word)
        _g(cs, v, (2, 6, 10, 14), block_words[s[4]], block_words[s[5]], zero_word)
        _g(cs, v, (3, 7, 11, 15), block_words[s[6]], block_words[s[7]], zero_word)
        _g(cs, v, (0, 5, 10, 15), block_words[s[8]], block_words[s[9]], zero_word)
        _g(cs, v, (1, 6, 11, 12), block_words[s[10]], block_words[s[11]], zero_word)
        _g(cs, v, (2, 7, 8, 13), block_words[s[12]], block_words[s[13]], zero_word)
        _g(cs, v, (3, 4, 9, 14), block_words[s[14]], block_words[s[15]], zero_word)

    return [
        xor_many(cs, xor_many(cs, h[i], v[i]), v[i + 8]) for i in range(8)
    ]


def blake2s(cs, input_bytes) -> list:
    """Blake2s-256 over a list of u8 variables; returns 32 u8 digest
    variables (reference mod.rs:36)."""
    register_blake2s_tables(cs)
    zero = cs.zero_var()
    zero_word = [zero] * 4

    h = [
        _const_word(cs, IV_0_TWIST if i == 0 else IV[i]) for i in range(8)
    ]

    data = list(input_bytes)
    num_blocks = max(1, (len(data) + BLOCK_SIZE - 1) // BLOCK_SIZE)
    for blk in range(num_blocks):
        chunk = data[blk * BLOCK_SIZE : (blk + 1) * BLOCK_SIZE]
        is_last = blk == num_blocks - 1
        if is_last:
            offset = len(data)
            chunk = chunk + [zero] * (BLOCK_SIZE - len(chunk))
        else:
            offset = (blk + 1) * BLOCK_SIZE
        words = [chunk[4 * i : 4 * i + 4] for i in range(16)]
        h = _compression(cs, h, words, offset, is_last, zero_word)

    out = []
    for w in h:
        out.extend(w)
    return out


def blake2s_digest_bytes(cs, digest) -> bytes:
    return bytes(int(cs.get_value(v)) for v in digest)
