"""Shared 4-bit-chunk plumbing used by the UIntX and SHA-256 gadgets.

The membership check rides the TriXor4 table (a chunk appearing in any key
column of a TriXor4 lookup is forced into [0,16)); recomposition is a chained
ReductionGate scan. Counterpart of the reference's per-gadget repetitions of
the same idiom (round_function.rs:153, :678; u32 decompositions).
"""

from __future__ import annotations

from ..cs.gates.simple import ReductionGate
from .tables import trixor4_table

MASK4 = 0xF


def ensure_trixor(cs) -> int:
    return cs.ensure_table("trixor4", trixor4_table)


def range_check_chunks_batched(cs, chunks, table_id=None):
    """4-bit membership checks through TriXor4, three chunks per lookup.

    When the CS has no lookup argument configured, falls back to boolean bit
    decomposition (4 booleans + a recomposition per chunk) so range-checked
    gadgets stay usable in lookup-free circuits."""
    if not chunks:
        return
    if not cs.lookup_params.is_enabled:
        from .num import Num

        for c in chunks:
            Num(c).spread_into_bits(cs, 4)
        return
    if table_id is None:
        table_id = ensure_trixor(cs)
    zero = cs.zero_var()
    for i in range(0, len(chunks), 3):
        batch = list(chunks[i : i + 3])
        while len(batch) < 3:
            batch.append(zero)
        cs.perform_lookup(table_id, batch)


def enforce_chunk_recomposition(cs, chunks, var, bits_per_chunk=4):
    """Enforce var == Σ chunk_i · 2^(bits·i) via a ReductionGate chain."""
    acc = None
    shift = 0
    rem = list(chunks)
    while rem:
        part, rem = rem[:3], rem[3:]
        vars4, cf = [], []
        if acc is not None:
            vars4.append(acc)
            cf.append(1)
        for c in part:
            vars4.append(c)
            cf.append(1 << shift)
            shift += bits_per_chunk
        while len(vars4) < 4:
            vars4.append(cs.zero_var())
            cf.append(0)
        if rem:
            acc = ReductionGate.reduce(cs, vars4, cf)
        else:
            ReductionGate.enforce_reduce(cs, vars4, cf, var)


def decompose_and_check(cs, var, num_bits):
    """Split var into range-checked 4-bit chunks + enforce recomposition."""
    assert num_bits % 4 == 0
    k = num_bits // 4
    chunks = cs.alloc_multiple_variables_without_values(k)

    def resolve(vals):
        x = vals[0]
        return [(x >> (4 * i)) & MASK4 for i in range(k)]

    from ..native import OP_SPLIT

    cs.set_values_with_dependencies(
        [var], chunks, resolve, native=(OP_SPLIT, (4,))
    )
    enforce_chunk_recomposition(cs, chunks, var)
    range_check_chunks_batched(cs, chunks)
    return chunks
