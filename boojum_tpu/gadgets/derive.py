"""Composite-gadget derivation: allocate / select / witness hooks.

Counterpart of the reference's `cs_derive` proc-macro crate (937 LoC:
`CSAllocatable`, `CSSelectable`, `WitnessHookable`,
`CSVarLengthEncodable` derives) and the gadget traits they implement
(`/root/reference/src/gadgets/traits/allocatable.rs:6`, `selectable.rs:8`,
`witnessable.rs:121`). Rust needs compile-time codegen for this; in python a
small structural recursion over dataclass fields does the same job at
runtime:

    @derive_gadget
    @dataclass
    class Point:
        x: Num
        y: Num

    p = Point.allocate(cs, {"x": 3, "y": 4})
    q = Point.select(cs, flag, p, r)
    hook = Point.witness_hook(cs, p); hook() -> {"x": 3, "y": 4}

Any field whose type provides `allocate`/`select`/`get_value` composes,
including nested derived gadgets, lists, and tuples.
"""

from __future__ import annotations

import dataclasses

from .boolean import Boolean
from .num import Num


def _allocate_value(cls, cs, witness):
    if dataclasses.is_dataclass(cls) and hasattr(cls, "allocate"):
        return cls.allocate(cs, witness)
    if hasattr(cls, "allocate_checked"):
        return cls.allocate_checked(cs, witness)
    if hasattr(cls, "allocate"):
        return cls.allocate(cs, witness)
    raise TypeError(f"field type {cls} is not allocatable")


def _select_value(cs, flag, a, b):
    if type(a) is not type(b):
        raise TypeError("select over mismatched types")
    if isinstance(a, (list, tuple)):
        out = [ _select_value(cs, flag, x, y) for x, y in zip(a, b) ]
        return type(a)(out)
    t = type(a)
    if hasattr(t, "select"):
        return t.select(cs, flag, a, b)
    raise TypeError(f"{t} is not selectable")


def _witness_value(cs, v):
    if isinstance(v, (list, tuple)):
        return type(v)(_witness_value(cs, x) for x in v)
    if dataclasses.is_dataclass(v) and hasattr(type(v), "witness_hook"):
        return type(v).witness_hook(cs, v)()
    if hasattr(v, "get_value"):
        return v.get_value(cs)
    raise TypeError(f"{type(v)} is not witnessable")


def encode_variables(v) -> list:
    """Flatten a gadget (or nested structure of gadgets) into its ordered
    list of circuit variables — the runtime face of the reference's
    `CSVarLengthEncodable` derive
    (`/root/reference/cs_derive/src/var_length_encodable/mod.rs`):
    field-recursive, deterministic order, variable total length. The
    encoding feeds commitment chains (queues) and public-input packing."""
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            out.extend(encode_variables(x))
        return out
    if isinstance(v, (Num, Boolean)):
        return [v.var]
    if isinstance(v, int):  # a raw variable place
        return [v]
    if dataclasses.is_dataclass(v):
        out = []
        for f in dataclasses.fields(v):
            out.extend(encode_variables(getattr(v, f.name)))
        return out
    if hasattr(v, "encode_vars"):
        return list(v.encode_vars())
    raise TypeError(f"{type(v)} is not var-length encodable")


def derive_gadget(cls):
    """Class decorator adding allocate / select / witness_hook /
    encoding_length / encode_vars to a dataclass of gadget fields (the
    runtime face of the reference's #[derive(CSAllocatable, CSSelectable,
    WitnessHookable, CSVarLengthEncodable)])."""
    assert dataclasses.is_dataclass(cls), "derive_gadget expects a dataclass"
    import typing

    hints = typing.get_type_hints(cls)
    fields = dataclasses.fields(cls)

    def allocate(cs, witness: dict):
        kwargs = {}
        for f in fields:
            kwargs[f.name] = _allocate_value(hints[f.name], cs, witness[f.name])
        return cls(**kwargs)

    def select(cs, flag: Boolean, a, b):
        kwargs = {
            f.name: _select_value(cs, flag, getattr(a, f.name), getattr(b, f.name))
            for f in fields
        }
        return cls(**kwargs)

    def witness_hook(cs, value):
        """Deferred witness getter (reference WitnessHookable): call the
        returned closure after synthesis to materialize the values."""

        def hook():
            return {
                f.name: _witness_value(cs, getattr(value, f.name))
                for f in fields
            }

        return hook

    def encode_vars(self):
        return encode_variables(self)

    def encoding_length(self) -> int:
        return len(encode_variables(self))

    cls.allocate = staticmethod(allocate)
    cls.select = staticmethod(select)
    cls.witness_hook = staticmethod(witness_hook)
    cls.encode_vars = encode_vars
    cls.encoding_length = encoding_length
    return cls


# Make the scalar gadgets compose: Num/Boolean already provide
# allocate/select/get_value with the right shapes.
__all__ = ["derive_gadget", "encode_variables"]
