"""Keccak-256 gadget.

Counterpart of `/root/reference/src/gadgets/keccak256/` (mod.rs:56 `keccak256`,
round_function.rs:19 `keccak_256_round_function`): the 1600-bit state is a
5x5 matrix of 64-bit lanes, each lane carried as 8 little-endian byte
variables; xor/and are 8-bit-table lookups (the field is ~64 bits so a sparse
base buys nothing — same trade the reference makes, round_function.rs:28-29),
bit rotations split bytes via per-split lookup tables and remerge with FMA
gates, and NOT(a) is `255 - a` on an arithmetic gate.

Keccak padding is the original 0x01 domain (Ethereum-style), NOT NIST SHA-3's
0x06 (reference mod.rs:70-79).
"""

from __future__ import annotations

from ..cs.gates.simple import FmaGate
from ..field import gl
from .byte_ops import (
    and_many,
    ensure_and8,
    ensure_byte_split,
    ensure_xor8,
    rotate_bytes_left,
    xor_many,
)

LANE_WIDTH = 5
BYTES_PER_WORD = 8
NUM_ROUNDS = 24
RATE_BYTES = 136
DIGEST_SIZE = 32

ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]


def register_keccak_tables(cs):
    """Register xor8/and8 and every byte-split table the rotations need."""
    ensure_xor8(cs)
    ensure_and8(cs)
    for split_at in range(1, 8):
        ensure_byte_split(cs, split_at)


def rotate_word(cs, word, r: int):
    """Rotate a 64-bit lane (8 LE byte vars) left by r bits
    (reference round_function.rs `rotate_word`)."""
    return rotate_bytes_left(cs, word, r)


def _not_byte(cs, v, neg_const):
    """255 - v (reference round_function.rs:103-106)."""
    one = cs.one_var()
    return FmaGate.fma(cs, one, v, neg_const, gl.P - 1, 1)


def keccak_1600_round(cs, state, round_constant: int):
    """One Keccak-f[1600] round over the 5x5x8 byte-variable state
    (reference round_function.rs:31)."""
    # theta
    c = []
    for i in range(LANE_WIDTH):
        tmp = xor_many(cs, state[i][0], state[i][1])
        tmp = xor_many(cs, tmp, state[i][2])
        tmp = xor_many(cs, tmp, state[i][3])
        tmp = xor_many(cs, tmp, state[i][4])
        c.append(tmp)
    c_rot = [rotate_word(cs, c[i], 1) for i in range(LANE_WIDTH)]
    d = [
        xor_many(cs, c[(i - 1) % LANE_WIDTH], c_rot[(i + 1) % LANE_WIDTH])
        for i in range(LANE_WIDTH)
    ]
    for i in range(LANE_WIDTH):
        for j in range(LANE_WIDTH):
            state[i][j] = xor_many(cs, state[i][j], d[i])

    # rho + pi (reference round_function.rs:78-90)
    i, j = 1, 0
    current = state[i][j]
    for idx in range(24):
        i, j = j, (2 * i + 3 * j) % LANE_WIDTH
        existing = state[i][j]
        rotation = (((idx + 1) * (idx + 2)) >> 1) % 64
        state[i][j] = rotate_word(cs, current, rotation)
        current = existing

    # chi
    neg_const = cs.allocate_constant((1 << 8) - 1)
    for j in range(LANE_WIDTH):
        t = [state[i][j] for i in range(LANE_WIDTH)]
        for i in range(LANE_WIDTH):
            nt = [_not_byte(cs, b, neg_const) for b in t[(i + 1) % LANE_WIDTH]]
            tmp = and_many(cs, nt, t[(i + 2) % LANE_WIDTH])
            state[i][j] = xor_many(cs, tmp, t[i])

    # iota
    rc = [
        cs.allocate_constant((round_constant >> (8 * b)) & 0xFF)
        for b in range(8)
    ]
    state[0][0] = xor_many(cs, state[0][0], rc)


def keccak_256_round_function(cs, state):
    for rc in ROUND_CONSTANTS:
        keccak_1600_round(cs, state, rc)


def keccak256(cs, input_bytes) -> list:
    """Keccak-256 over a list of u8 variables; returns 32 u8 digest variables
    (reference mod.rs:56)."""
    register_keccak_tables(cs)
    zero = cs.zero_var()
    state = [
        [[zero] * BYTES_PER_WORD for _ in range(LANE_WIDTH)]
        for _ in range(LANE_WIDTH)
    ]

    padded = list(input_bytes)
    padlen = RATE_BYTES - len(padded) % RATE_BYTES
    if padlen == 1:
        padded.append(cs.allocate_constant(0x81))
    else:
        padded.append(cs.allocate_constant(0x01))
        padded.extend([zero] * (padlen - 2))
        padded.append(cs.allocate_constant(0x80))
    assert len(padded) % RATE_BYTES == 0

    for off in range(0, len(padded), RATE_BYTES):
        block = padded[off : off + RATE_BYTES]
        for j in range(LANE_WIDTH):
            for i in range(LANE_WIDTH):
                w = i + LANE_WIDTH * j
                if w < RATE_BYTES // BYTES_PER_WORD:
                    lane = block[w * BYTES_PER_WORD : (w + 1) * BYTES_PER_WORD]
                    state[i][j] = xor_many(cs, state[i][j], lane)
        keccak_256_round_function(cs, state)

    out = []
    for i in range(DIGEST_SIZE // BYTES_PER_WORD):
        out.extend(state[i][0])
    return out


def keccak256_digest_bytes(cs, digest) -> bytes:
    """Materialize the witness digest (test helper)."""
    return bytes(int(cs.get_value(v)) for v in digest)
