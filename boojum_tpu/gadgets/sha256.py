"""SHA-256 circuit gadget.

Counterpart of `/root/reference/src/gadgets/sha256/mod.rs:35` (sha256) and
`round_function.rs:53` (round_function): words live as u32 variables, all
bitwise structure goes through width-4 lookup sub-arguments over 4-bit-chunk
tables (TriXor4 / Ch4 / Maj4 / Split4BitChunk), rotations are performed by a
9-piece decomposition + chunk renumbering + one table-merged chunk
(round_function.rs:417 split_and_rotate), and u32 range checks ride the
TriXor4 table (membership in [0,16) per chunk).

This file re-derives the reference's circuit layout so the resulting trace
geometry (and hence the benchmark) is comparable; every helper notes its
reference counterpart.
"""

from __future__ import annotations

from ..cs.gates.simple import FmaGate, ReductionGate
from .chunk_utils import range_check_chunks_batched
from .tables import ch4_table, maj4_table, split4bit_table, trixor4_table

SHA256_ROUNDS = 64
SHA256_BLOCK_SIZE = 64
SHA256_DIGEST_SIZE = 32

INITIAL_STATE = [
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
]

ROUND_CONSTANTS = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
]

MASK4 = 0xF


def register_sha256_tables(cs):
    """Add the five SHA tables if not present; returns their ids."""
    ids = {}
    for name, build in (
        ("trixor4", trixor4_table),
        ("ch4", ch4_table),
        ("maj4", maj4_table),
        ("split4bit_at1", lambda: split4bit_table(1)),
        ("split4bit_at2", lambda: split4bit_table(2)),
    ):
        ids[name] = cs.ensure_table(name, build)
    return ids


class _Sha256Ctx:
    """Per-circuit handles: table ids + shared constants."""

    def __init__(self, cs):
        # sha256 words are u32 variables — one field element per 32-bit
        # value. BabyBear (p ≈ 2^31) cannot represent them; fail at
        # synthesis with a clear error (ISSUE 20 field-capacity guard).
        require = getattr(cs, "require_field_bits", None)
        if require is not None:
            require(32, "sha256 gadget")
        ids = register_sha256_tables(cs)
        self.cs = cs
        self.trixor = ids["trixor4"]
        self.ch = ids["ch4"]
        self.maj = ids["maj4"]
        self.split = {1: ids["split4bit_at1"], 2: ids["split4bit_at2"]}
        self.zero = cs.zero_var()
        self.one = cs.one_var()

    # -- chunk helpers ------------------------------------------------------

    def tri_xor_many(self, a, b, c):
        """Per-chunk TriXor4 lookups (round_function.rs:620 tri_xor_many)."""
        cs = self.cs
        return [cs.perform_lookup(self.trixor, [x, y, z])[0]
                for x, y, z in zip(a, b, c)]

    def ch_many(self, a, b, c):
        cs = self.cs
        return [cs.perform_lookup(self.ch, [x, y, z])[0]
                for x, y, z in zip(a, b, c)]

    def maj_many(self, a, b, c):
        cs = self.cs
        return [cs.perform_lookup(self.maj, [x, y, z])[0]
                for x, y, z in zip(a, b, c)]

    def range_check_chunks(self, chunks):
        """Batch 4-bit membership checks through TriXor4, 3 chunks a pop
        (round_function.rs:153 'range check small pieces')."""
        range_check_chunks_batched(self.cs, chunks, self.trixor)

    def merge_4bit_chunk(self, low, high, split_at, swap_output):
        """Merge two sub-4-bit pieces via Split4BitChunk (round_function.rs:566)."""
        cs = self.cs
        merged = cs.alloc_multiple_variables_without_values(2)

        def resolve(vals, s=split_at):
            lo, hi = vals
            return [lo | (hi << s), hi | (lo << (4 - s))]

        cs.set_values_with_dependencies([low, high], merged, resolve)
        # table row: (x, x & mask, x >> s, reversed)
        cs.enforce_lookup(
            self.split[split_at], [merged[0], low, high, merged[1]]
        )
        return merged[1] if swap_output else merged[0]

    # -- u32 (de)composition ------------------------------------------------

    def u32_to_chunks(self, v):
        """Decompose a u32 var into 8 LE 4-bit chunks + enforce recomposition
        (round_function.rs:352 uint32_into_4bit_chunks). Chunks are NOT
        range-checked here (callers batch that through lookups)."""
        cs = self.cs
        chunks = cs.alloc_multiple_variables_without_values(8)

        def resolve(vals):
            x = vals[0]
            return [(x >> (4 * i)) & MASK4 for i in range(8)]

        cs.set_values_with_dependencies([v], chunks, resolve)
        self._enforce_u32_from_chunks(chunks, v)
        return chunks

    def _enforce_u32_from_chunks(self, chunks, v):
        cs = self.cs
        to_u16 = [1, 1 << 4, 1 << 8, 1 << 12]
        low = ReductionGate.reduce(cs, chunks[:4], to_u16)
        high = ReductionGate.reduce(cs, chunks[4:], to_u16)
        FmaGate.enforce_fma(cs, self.one, high, low, v, 1 << 16, 1)

    def u32_from_chunks(self, chunks):
        """8 LE 4-bit chunks -> u32 var (round_function.rs:326)."""
        cs = self.cs
        to_u16 = [1, 1 << 4, 1 << 8, 1 << 12]
        low = ReductionGate.reduce(cs, chunks[:4], to_u16)
        high = ReductionGate.reduce(cs, chunks[4:], to_u16)
        return FmaGate.fma(cs, self.one, high, low, 1 << 16, 1)

    def split_and_rotate(self, v, rotation):
        """Right-rotation by chunk renumbering (round_function.rs:417):
        decompose as |rm|4|4|4|4|4|4|4|4-rm| pieces, enforce recomposition,
        merge the boundary pieces through the split table, renumber."""
        cs = self.cs
        rm = rotation % 4
        assert rm != 0
        aligned = cs.alloc_multiple_variables_without_values(7)
        dec_low = cs.alloc_variable_without_value()
        dec_high = cs.alloc_variable_without_value()

        def resolve(vals, rm=rm):
            x = vals[0]
            out = [x & ((1 << rm) - 1)]
            x >>= rm
            for _ in range(7):
                out.append(x & MASK4)
                x >>= 4
            out.append(x)  # < 2^(4-rm)
            return out

        cs.set_values_with_dependencies(
            [v], [dec_low] + aligned + [dec_high], resolve
        )
        # recomposition: v = dec_low + sum aligned_i·2^(rm+4i) + dec_high·2^(rm+28)
        shift = 0
        coeffs = []
        for i in range(4):
            coeffs.append(1 << shift)
            shift += rm if i == 0 else 4
        t = ReductionGate.reduce(cs, [dec_low] + aligned[:3], coeffs)
        coeffs = [1]
        for _ in range(3):
            coeffs.append(1 << shift)
            shift += 4
        t = ReductionGate.reduce(cs, [t] + aligned[3:6], coeffs)
        coeffs = [1, 1 << shift, 1 << (shift + 4), 0]
        ReductionGate.enforce_reduce(
            cs, [t, aligned[6], dec_high, self.zero], coeffs, v
        )
        # merge boundary pieces into one aligned chunk
        if rm == 1:
            merged = self.merge_4bit_chunk(dec_low, dec_high, 1, True)
        elif rm == 2:
            merged = self.merge_4bit_chunk(dec_high, dec_low, 2, False)
        else:  # rm == 3
            merged = self.merge_4bit_chunk(dec_high, dec_low, 1, False)
        full = rotation // 4
        result = [None] * 8
        for i, el in enumerate(aligned):
            result[(8 - full + i) % 8] = el
        result[(8 - full - 1) % 8] = merged
        return result, dec_low, dec_high

    # -- range checks -------------------------------------------------------

    def split_36_unchecked(self, v):
        """v = low + 2^32·high with no range enforcement yet
        (round_function.rs:771)."""
        cs = self.cs
        low = cs.alloc_variable_without_value()
        high = cs.alloc_variable_without_value()

        def resolve(vals):
            return [vals[0] & 0xFFFFFFFF, vals[0] >> 32]

        cs.set_values_with_dependencies([v], [low, high], resolve)
        FmaGate.enforce_fma(cs, self.one, high, low, v, 1 << 32, 1)
        return low, high

    def range_check_36(self, v):
        """Split a ≤36-bit value into 9 checked 4-bit chunks; returns the u32
        part (round_function.rs:692)."""
        cs = self.cs
        chunks = cs.alloc_multiple_variables_without_values(9)

        def resolve(vals):
            x = vals[0]
            return [(x >> (4 * i)) & MASK4 for i in range(9)]

        cs.set_values_with_dependencies([v], chunks, resolve)
        to_u16 = [1, 1 << 4, 1 << 8, 1 << 12]
        low = ReductionGate.reduce(cs, chunks[:4], to_u16)
        high = ReductionGate.reduce(cs, chunks[4:8], to_u16)
        u32_part = FmaGate.fma(cs, self.one, high, low, 1 << 16, 1)
        FmaGate.enforce_fma(cs, self.one, chunks[8], u32_part, v, 1 << 32, 1)
        self.tri_xor_many([chunks[0]], [chunks[1]], [chunks[2]])
        self.tri_xor_many([chunks[3]], [chunks[4]], [chunks[5]])
        self.tri_xor_many([chunks[6]], [chunks[7]], [chunks[8]])
        return u32_part, chunks

    def range_check_u32(self, v):
        """Full u32 decomposition + 4-bit checks (round_function.rs:678);
        returns the 8 chunks."""
        chunks = self.u32_to_chunks(v)
        self.tri_xor_many([chunks[0]], [chunks[1]], [chunks[2]])
        self.tri_xor_many([chunks[3]], [chunks[4]], [chunks[5]])
        self.tri_xor_many([chunks[6]], [chunks[7]], [chunks[0]])
        return chunks


def round_function(ctx: _Sha256Ctx, state, message_block, last_round):
    """One SHA-256 compression round over 16 message words
    (round_function.rs:53). state: list of 8 u32 vars, updated in place.
    Returns the 64 LE 4-bit digest chunks when last_round."""
    cs = ctx.cs
    zero = ctx.zero
    expanded = list(message_block) + [None] * (SHA256_ROUNDS - 16)
    unconstrained = []

    for idx in range(16, SHA256_ROUNDS):
        t0 = expanded[idx - 15]
        t0_rot7, _low7, t0_rot7_high = ctx.split_and_rotate(t0, 7)
        t0_rot18, _, _ = ctx.split_and_rotate(t0, 18)
        t0_shift3 = [t0_rot7[(7 + i) % 8] for i in range(7)] + [t0_rot7_high]
        s0_chunks = ctx.tri_xor_many(t0_rot7, t0_rot18, t0_shift3)

        t1 = expanded[idx - 2]
        t1_rot17, _, _ = ctx.split_and_rotate(t1, 17)
        t1_rot19, _, _ = ctx.split_and_rotate(t1, 19)
        t1_rot10, _, t1_rot10_high = ctx.split_and_rotate(t1, 10)
        t1_shift10 = list(t1_rot10)
        t1_shift10[7] = zero
        t1_shift10[6] = zero
        t1_shift10[5] = t1_rot10_high
        s1_chunks = ctx.tri_xor_many(t1_rot17, t1_rot19, t1_shift10)

        s0 = ctx.u32_from_chunks(s0_chunks)
        s1 = ctx.u32_from_chunks(s1_chunks)
        word = ReductionGate.reduce(
            cs, [s0, s1, expanded[idx - 7], expanded[idx - 16]], [1, 1, 1, 1]
        )
        if idx + 2 >= SHA256_ROUNDS:
            u32_part, _ = ctx.range_check_36(word)
        else:
            u32_part, high = ctx.split_36_unchecked(word)
            unconstrained.append(high)
        expanded[idx] = u32_part

    ctx.range_check_chunks(unconstrained)

    a, b, c, d, e, f, g, h = state

    for rnd in range(SHA256_ROUNDS):
        e_rot6, _, _ = ctx.split_and_rotate(e, 6)
        e_rot11, _, _ = ctx.split_and_rotate(e, 11)
        e_rot25, _, _ = ctx.split_and_rotate(e, 25)
        s1 = ctx.u32_from_chunks(ctx.tri_xor_many(e_rot6, e_rot11, e_rot25))

        e_dec = ctx.u32_to_chunks(e)
        f_dec = ctx.u32_to_chunks(f)
        g_dec = ctx.u32_to_chunks(g)
        ch = ctx.u32_from_chunks(ctx.ch_many(e_dec, f_dec, g_dec))

        rc = cs.allocate_constant(ROUND_CONSTANTS[rnd])
        tmp1 = ReductionGate.reduce(cs, [h, s1, ch, rc], [1, 1, 1, 1])
        tmp1 = FmaGate.fma(cs, ctx.one, tmp1, expanded[rnd], 1, 1)
        t = FmaGate.fma(cs, ctx.one, tmp1, d, 1, 1)
        new_e, _ = ctx.range_check_36(t)

        a_rot2, _, _ = ctx.split_and_rotate(a, 2)
        a_rot13, _, _ = ctx.split_and_rotate(a, 13)
        a_rot22 = [a_rot2[(i + 5) % 8] for i in range(8)]
        s0 = ctx.u32_from_chunks(ctx.tri_xor_many(a_rot2, a_rot13, a_rot22))

        a_dec = ctx.u32_to_chunks(a)
        b_dec = ctx.u32_to_chunks(b)
        c_dec = ctx.u32_to_chunks(c)
        maj = ctx.u32_from_chunks(ctx.maj_many(a_dec, b_dec, c_dec))

        t = ReductionGate.reduce(cs, [s0, maj, tmp1, zero], [1, 1, 1, 0])
        new_a, _ = ctx.range_check_36(t)

        h, g, f, e = g, f, e, new_e
        d, c, b, a = c, b, a, new_a

    # fold into state (mod 2^32), range checking d & h fully
    final_d_dec = final_h_dec = None
    unchecked = []
    for i, src in enumerate([a, b, c, d, e, f, g, h]):
        tmp = FmaGate.fma(cs, ctx.one, state[i], src, 1, 1)
        tmp, high = ctx.split_36_unchecked(tmp)
        unchecked.append(high)
        if i == 3:
            final_d_dec = ctx.range_check_u32(tmp)
        if i == 7:
            final_h_dec = ctx.range_check_u32(tmp)
        state[i] = tmp
    ctx.range_check_chunks(unchecked)

    if not last_round:
        return None
    le_chunks = []
    to_check = []
    for i, el in enumerate(state):
        if i == 3:
            dec = final_d_dec
        elif i == 7:
            dec = final_h_dec
        else:
            dec = ctx.u32_to_chunks(el)
            to_check.extend(dec)
        le_chunks.extend(dec)
    ctx.range_check_chunks(to_check)
    return le_chunks


def allocate_u8_input(cs, data: bytes):
    """Allocate input bytes as range-checked u8 variables (the reference
    bench allocates checked UInt8 witnesses, sha256/mod.rs:330)."""
    ctx = _Sha256Ctx(cs)
    out = []
    chunks_to_check = []
    for byte in data:
        v = cs.alloc_variable_with_value(byte)
        lo = cs.alloc_variable_with_value(byte & MASK4)
        hi = cs.alloc_variable_with_value(byte >> 4)
        FmaGate.enforce_fma(cs, ctx.one, hi, lo, v, 1 << 4, 1)
        chunks_to_check.extend([lo, hi])
        out.append(v)
    ctx.range_check_chunks(chunks_to_check)
    return out


def sha256(cs, input_bytes):
    """Hash a list of u8 variables; returns 32 u8 digest variables
    (reference sha256/mod.rs:35)."""
    ctx = _Sha256Ctx(cs)
    msg = list(input_bytes)
    ln = len(msg)
    last = ln % SHA256_BLOCK_SIZE
    if last <= SHA256_BLOCK_SIZE - 1 - 8:
        zeros = SHA256_BLOCK_SIZE - 1 - 8 - last
    else:
        zeros = 2 * SHA256_BLOCK_SIZE - 1 - 8 - last
    msg.append(cs.allocate_constant(0x80))
    zero_byte = cs.allocate_constant(0x00)
    msg.extend([zero_byte] * zeros)
    for byte in (ln * 8).to_bytes(8, "big"):
        msg.append(cs.allocate_constant(byte))
    assert len(msg) % SHA256_BLOCK_SIZE == 0
    num_blocks = len(msg) // SHA256_BLOCK_SIZE

    state = [cs.allocate_constant(v) for v in INITIAL_STATE]
    final_chunks = None
    for blk in range(num_blocks):
        block = msg[blk * SHA256_BLOCK_SIZE : (blk + 1) * SHA256_BLOCK_SIZE]
        words = []
        for i in range(16):
            b0, b1, b2, b3 = block[4 * i : 4 * i + 4]
            words.append(
                ReductionGate.reduce(
                    cs, [b0, b1, b2, b3],
                    [1 << 24, 1 << 16, 1 << 8, 1],
                )
            )
        final_chunks = round_function(
            ctx, state, words, blk == num_blocks - 1
        )

    # chunks -> bytes, big-endian within each word (sha256/mod.rs:88)
    output = []
    for w in range(8):
        word_chunks = final_chunks[8 * w : 8 * w + 8]
        word_bytes = []
        for k in range(4):
            low, high = word_chunks[2 * k], word_chunks[2 * k + 1]
            word_bytes.append(FmaGate.fma(cs, ctx.one, high, low, 1 << 4, 1))
        output.extend(reversed(word_bytes))
    return output


def sha256_digest_bytes(cs, digest_vars) -> bytes:
    """Read back the digest witness values as bytes."""
    return bytes(cs.get_value(v) for v in digest_vars)
