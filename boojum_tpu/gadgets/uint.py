"""Fixed-width unsigned integer gadgets: UInt8 / UInt16 / UInt32 (reference
`/root/reference/src/gadgets/u8,u16,u32/`, 3,249 LoC across widths).

Range correctness comes from 4-bit-chunk lookups against the TriXor4 table
(the same strategy the SHA-256 circuit uses — membership in [0,16) per
chunk); carry arithmetic uses the UIntXAdd / U32 gates.
"""

from __future__ import annotations

from ..cs.gates.simple import ReductionGate, SelectionGate
from ..cs.gates.u32 import U32AddGate, U32FmaGate, U32SubGate, UIntXAddGate
from .boolean import Boolean
from .num import Num
from .chunk_utils import decompose_and_check as _decompose_and_check


class UIntX:
    """Common machinery; subclasses pin WIDTH."""

    WIDTH = 0
    __slots__ = ("var",)

    def __init__(self, var: int):
        self.var = var

    @classmethod
    def allocate_checked(cls, cs, value: int) -> "UIntX":
        assert 0 <= value < (1 << cls.WIDTH)
        v = cs.alloc_variable_with_value(value)
        _decompose_and_check(cs, v, cls.WIDTH)
        return cls(v)

    @classmethod
    def allocated_constant(cls, cs, value: int) -> "UIntX":
        assert 0 <= value < (1 << cls.WIDTH)
        return cls(cs.allocate_constant(value))

    @classmethod
    def from_variable_checked(cls, cs, var: int) -> "UIntX":
        _decompose_and_check(cs, var, cls.WIDTH)
        return cls(var)

    def get_value(self, cs) -> int:
        return cs.get_value(self.var)

    def into_num(self) -> Num:
        return Num(self.var)

    # -- arithmetic (checked) ----------------------------------------------

    def add(self, cs, other):
        """(sum, carry_out boolean)."""
        gate = UIntXAddGate(self.WIDTH) if self.WIDTH != 32 else None
        if gate is None:
            c, cout = U32AddGate.add(cs, self.var, other.var, cs.zero_var())
        else:
            c, cout = gate.add(cs, self.var, other.var, cs.zero_var())
        _decompose_and_check(cs, c, self.WIDTH)
        return type(self)(c), Boolean(cout)

    def sub(self, cs, other):
        """(difference, borrow_out boolean)."""
        assert self.WIDTH == 32, "sub gate is 32-bit"
        c, bout = U32SubGate.sub(cs, self.var, other.var, cs.zero_var())
        _decompose_and_check(cs, c, self.WIDTH)
        return type(self)(c), Boolean(bout)

    @staticmethod
    def select(cs, flag: Boolean, a, b):
        assert type(a) is type(b)
        return type(a)(SelectionGate.select(cs, flag.var, a.var, b.var))


class UInt8(UIntX):
    WIDTH = 8


class UInt16(UIntX):
    WIDTH = 16


class UInt32(UIntX):
    WIDTH = 32

    @staticmethod
    def from_be_bytes(cs, bytes4) -> "UInt32":
        """4 UInt8 -> u32 (reference u32/mod.rs from_be_bytes)."""
        v = ReductionGate.reduce(
            cs, [b.var for b in bytes4], [1 << 24, 1 << 16, 1 << 8, 1]
        )
        return UInt32(v)

    def to_le_bytes(self, cs) -> list:
        """Decompose into 4 checked UInt8 (LE)."""
        outs = cs.alloc_multiple_variables_without_values(4)

        def resolve(vals):
            x = vals[0]
            return [(x >> (8 * i)) & 0xFF for i in range(4)]

        from ..native import OP_SPLIT

        cs.set_values_with_dependencies(
            [self.var], outs, resolve, native=(OP_SPLIT, (8,))
        )
        ReductionGate.enforce_reduce(
            cs, list(outs), [1, 1 << 8, 1 << 16, 1 << 24], self.var
        )
        return [UInt8.from_variable_checked(cs, o) for o in outs]

    def fma(self, cs, other: "UInt32", addend: "UInt32"):
        """(low, high) of self·other + addend (reference u32_fma.rs)."""
        low, high = U32FmaGate.fma(
            cs, self.var, other.var, addend.var, cs.zero_var()
        )
        _decompose_and_check(cs, low, 32)
        _decompose_and_check(cs, high, 32)
        return UInt32(low), UInt32(high)
