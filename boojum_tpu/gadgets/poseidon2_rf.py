"""Poseidon2 circuit round function + sponge gadget.

Counterpart of `/root/reference/src/gadgets/poseidon2/mod.rs` (circuit round
function delegating to the flattened gate) and the generic algebraic sponge
(`/root/reference/src/algebraic_props/sponge.rs`) instantiated over circuit
variables: rate 8 / capacity 4 / overwrite mode, bit-compatible with the
device sponge (`boojum_tpu.hashes.poseidon2`) and the host mirror
(`Poseidon2SpongeHost`) — the recursion circuit's transcript and tree hasher
hash exactly like the prover's.
"""

from __future__ import annotations

from ..cs.gates.poseidon2_flat import SW, Poseidon2FlattenedGate

RATE = 8
CAPACITY = 4


def circuit_permutation(cs, state_vars):
    """One width-12 permutation over circuit variables (one flattened-gate
    instance)."""
    return Poseidon2FlattenedGate.permutation(cs, state_vars)


class CircuitPoseidon2Sponge:
    """Overwrite-mode sponge over circuit variables (reference
    sponge.rs:172 generic sponge; absorb order matches Poseidon2SpongeHost)."""

    def __init__(self, cs):
        self.cs = cs
        zero = cs.zero_var()
        self.state = [zero] * SW
        self.buffer: list = []

    def absorb(self, variables):
        self.buffer.extend(variables)
        while len(self.buffer) >= RATE:
            chunk, self.buffer = self.buffer[:RATE], self.buffer[RATE:]
            self.state = circuit_permutation(
                self.cs, chunk + self.state[RATE:]
            )

    def finalize(self, n=CAPACITY):
        if self.buffer:
            zero = self.cs.zero_var()
            pad = [zero] * (RATE - len(self.buffer))
            self.state = circuit_permutation(
                self.cs, self.buffer + pad + self.state[RATE:]
            )
            self.buffer = []
        return self.state[:n]


def circuit_hash_leaf(cs, variables, n=CAPACITY):
    sp = CircuitPoseidon2Sponge(cs)
    sp.absorb(list(variables))
    return sp.finalize(n)


def circuit_hash_node(cs, left, right):
    sp = CircuitPoseidon2Sponge(cs)
    sp.absorb(list(left) + list(right))
    return sp.finalize(CAPACITY)
