"""Circuit-variable face of the field-like ops contract.

Counterpart of the reference's `NumAsFieldWrapper` / `NumExtAsFieldWrapper`
(`/root/reference/src/gadgets/num/prime_field_like.rs`): the same ops duck
type that drives gate evaluators over scalars (`ScalarOps`), device arrays
(`ArrayOps`) and verifier openings (`ExtScalarOps`) — here over circuit
variables, so *verifier formulas run inside a circuit*. This is the engine of
the recursive verifier: `gate.evaluate(CircuitExtOps(cs), row_of_opening_vars,
dst)` re-emits the inner circuit's quotient constraints as gadget constraints.

Base ops lower to FMA gates; extension ops are pairs (c0, c1) over
F_p[w]/(w^2 - 7) with schoolbook mul fused into 4 FMA gates.
"""

from __future__ import annotations

from ..cs.gates.simple import FmaGate, SelectionGate
from ..field import gl

NON_RESIDUE = 7


class CircuitOps:
    """Base-field ops over variable ids (bound to a CS)."""

    def __init__(self, cs):
        self.cs = cs

    def zero(self):
        return self.cs.zero_var()

    def one(self):
        return self.cs.one_var()

    def constant(self, v: int):
        return self.cs.allocate_constant(v % gl.P)

    def add(self, a, b):
        return FmaGate.fma(self.cs, self.one(), a, b, 1, 1)

    def sub(self, a, b):
        return FmaGate.fma(self.cs, self.one(), b, a, gl.P - 1, 1)

    def mul(self, a, b):
        return FmaGate.fma(self.cs, a, b, self.zero(), 1, 0)

    def neg(self, a):
        return FmaGate.fma(self.cs, self.one(), a, self.zero(), gl.P - 1, 0)

    def double(self, a):
        return FmaGate.fma(self.cs, self.one(), a, self.zero(), 2, 0)

    # -- extras beyond the evaluator contract -------------------------------

    def fma(self, a, b, c, ca=1, cc=1):
        """ca·a·b + cc·c."""
        return FmaGate.fma(self.cs, a, b, c, ca, cc)

    def mul_by_constant(self, a, k: int):
        return FmaGate.fma(self.cs, self.one(), a, self.zero(), k, 0)

    def enforce_equal(self, a, b):
        """a − b = 0 as one FMA row with an existing-variable rhs."""
        FmaGate.enforce_fma(self.cs, self.one(), a, b, a, 0, 1)

    def enforce_zero(self, a):
        FmaGate.enforce_fma(self.cs, self.one(), a, self.zero(), a, 0, 0)

    def inv(self, a):
        """Witness inverse with a·a_inv = 1 enforced (nonzero input only —
        verifier-side denominators)."""
        cs = self.cs
        iv = cs.alloc_variable_without_value()
        cs.set_values_with_dependencies([a], [iv], lambda v: [gl.inv(v[0])])
        FmaGate.enforce_fma(cs, a, iv, self.zero(), self.one(), 1, 0)
        return iv

    def select(self, flag, a, b):
        return SelectionGate.select(self.cs, flag, a, b)


class CircuitExtOps:
    """GF(p^2) ops over (c0_var, c1_var) pairs; w^2 = 7."""

    def __init__(self, cs):
        self.cs = cs
        self.base = CircuitOps(cs)

    def zero(self):
        z = self.cs.zero_var()
        return (z, z)

    def one(self):
        return (self.cs.one_var(), self.cs.zero_var())

    def constant(self, v: int):
        return (self.cs.allocate_constant(v % gl.P), self.cs.zero_var())

    def from_base_constants(self, c0: int, c1: int):
        return (
            self.cs.allocate_constant(c0 % gl.P),
            self.cs.allocate_constant(c1 % gl.P),
        )

    def from_base_var(self, v):
        return (v, self.cs.zero_var())

    def add(self, a, b):
        return (self.base.add(a[0], b[0]), self.base.add(a[1], b[1]))

    def sub(self, a, b):
        return (self.base.sub(a[0], b[0]), self.base.sub(a[1], b[1]))

    def neg(self, a):
        return (self.base.neg(a[0]), self.base.neg(a[1]))

    def double(self, a):
        return (self.base.double(a[0]), self.base.double(a[1]))

    def mul(self, a, b):
        """(a0 + a1 w)(b0 + b1 w): c0 = a0 b0 + 7 a1 b1, c1 = a0 b1 + a1 b0
        — four FMA gates."""
        t = self.base.fma(a[0], b[0], self.cs.zero_var(), 1, 0)
        c0 = self.base.fma(a[1], b[1], t, NON_RESIDUE, 1)
        u = self.base.fma(a[0], b[1], self.cs.zero_var(), 1, 0)
        c1 = self.base.fma(a[1], b[0], u, 1, 1)
        return (c0, c1)

    def mul_by_base(self, a, b_var):
        return (self.base.mul(a[0], b_var), self.base.mul(a[1], b_var))

    def mul_by_base_constant(self, a, k: int):
        return (
            self.base.mul_by_constant(a[0], k),
            self.base.mul_by_constant(a[1], k),
        )

    def inv(self, a):
        """Witness ext inverse with a·a_inv = 1 enforced."""
        cs = self.cs
        iv0 = cs.alloc_variable_without_value()
        iv1 = cs.alloc_variable_without_value()

        def resolve(vals):
            from ..field import extension as ext_f

            return list(ext_f.inv_s((vals[0], vals[1])))

        cs.set_values_with_dependencies([a[0], a[1]], [iv0, iv1], resolve)
        prod = self.mul(a, (iv0, iv1))
        self.base.enforce_equal(prod[0], cs.one_var())
        self.base.enforce_zero(prod[1])
        return (iv0, iv1)

    def pow(self, a, e: int):
        """Square-and-multiply with a circuit mul per step."""
        assert e >= 0
        if e == 0:
            return self.one()
        result = None
        cur = a
        while e:
            if e & 1:
                result = cur if result is None else self.mul(result, cur)
            e >>= 1
            if e:
                cur = self.mul(cur, cur)
        return result

    def enforce_equal(self, a, b):
        self.base.enforce_equal(a[0], b[0])
        self.base.enforce_equal(a[1], b[1])

    def select(self, flag, a, b):
        return (
            self.base.select(flag, a[0], b[0]),
            self.base.select(flag, a[1], b[1]),
        )

    def get_value(self, a):
        return (self.cs.get_value(a[0]), self.cs.get_value(a[1]))
