"""Non-native field arithmetic over 16-bit limbs.

Counterpart of `/root/reference/src/gadgets/non_native_field/` (traits +
`implementations/implementation_u16.rs`, 2,093 LoC): arithmetic in a foreign
prime field (secp256k1 base/scalar, BN254, …) encoded as vectors of 16-bit
limb variables over Goldilocks.

Design (same math, different factoring than the reference's lazy-bound
tracker): every operation enforces one integer congruence
`EXPR = q·m + r` through a limb-column carry chain —

  Σ_k (expr_k − (q·m)_k − r_k)·2^{16k} = 0

checked column by column with bounded signed carries in offset form
(carry + 2^B range-checked to B+2 bits). Every column constraint stays far
below the Goldilocks modulus, so field equality IS integer equality; the
telescoped chain with a zero final carry proves the congruence exactly.
Limb products for `mul` are FMA variables; (q·m)_k terms are constant-coeff
linear combinations (m is a circuit constant), so reduction gates carry them.

Results always come out as fresh 16-bit-checked limbs with value < 2^(16·N)
(not necessarily < m — canonicity is enforced on demand via
`enforce_reduced`, mirroring the reference's lazy normalization).
"""

from __future__ import annotations

from ..cs.gates.simple import FmaGate, ReductionGate
from ..cs.gates.u32 import UIntXAddGate
from ..field import gl
from .boolean import Boolean
from .chunk_utils import decompose_and_check, range_check_chunks_batched
from .num import Num

LIMB_BITS = 16
LIMB = 1 << LIMB_BITS
CARRY_OFFSET_BITS = 22  # |carry| < 2^22 given <= 33 products of 2^32 per col
CARRY_CHECK_BITS = 24  # offset carries range-checked to this many bits


class NNFParams:
    def __init__(self, modulus: int, name: str = "nnf"):
        self.modulus = modulus
        self.name = name
        self.num_limbs = (modulus.bit_length() + LIMB_BITS - 1) // LIMB_BITS
        self.m_limbs = [
            (modulus >> (LIMB_BITS * i)) & (LIMB - 1)
            for i in range(self.num_limbs)
        ]


def _limbs_of(value: int, n: int):
    return [(value >> (LIMB_BITS * i)) & (LIMB - 1) for i in range(n)]


class _LinAcc:
    """Accumulates Σ coeff·var + const into a chained reduction scan."""

    def __init__(self, cs):
        self.cs = cs
        self.items: list = []
        self.const = 0

    def add_term(self, var, coeff: int):
        c = coeff % gl.P
        if c:
            self.items.append((var, c))

    def add_const(self, v: int):
        self.const = (self.const + v) % gl.P

    def build(self):
        cs = self.cs
        items = list(self.items)
        if self.const:
            items.append((cs.one_var(), self.const))
        if not items:
            return cs.zero_var()
        acc = None
        while items:
            chunk, items = items[:3], items[3:]
            vars4 = [v for v, _ in chunk]
            cf = [c for _, c in chunk]
            if acc is not None:
                vars4 = [acc] + vars4
                cf = [1] + cf
            while len(vars4) < 4:
                vars4.append(cs.zero_var())
                cf.append(0)
            acc = ReductionGate.reduce(cs, vars4, cf)
        return acc

    def enforce_zero(self):
        v = self.build()
        FmaGate.enforce_fma(
            self.cs, self.cs.one_var(), v, self.cs.zero_var(), self.cs.zero_var(), 0, 1
        )


def _enforce_congruence(cs, columns, q_limbs, r_limbs, params):
    """Enforce Σ columns_k·2^{16k} = q·m + Σ r_k·2^{16k} as integers.

    columns: list over k of `_LinAcc`-style term lists
    [(var, coeff), ...] plus a constant, all guaranteed nonneg-bounded well
    below p per column. q_limbs / r_limbs are 16-bit-checked variables.
    """
    n = params.num_limbs
    num_cols = max(len(columns), len(q_limbs) + n - 1, n)
    offset = 1 << CARRY_OFFSET_BITS
    prev_s = None  # offset carry variable entering the column
    for k in range(num_cols):
        acc = _LinAcc(cs)
        if k < len(columns):
            terms, const = columns[k]
            for var, coeff in terms:
                acc.add_term(var, coeff)
            acc.add_const(const)
        # - (q·m)_k
        for i, qv in enumerate(q_limbs):
            j = k - i
            if 0 <= j < n and params.m_limbs[j]:
                acc.add_term(qv, -params.m_limbs[j])
        # - r_k
        if k < len(r_limbs):
            acc.add_term(r_limbs[k], -1)
        # + carry_in  (carry = s_prev - 2^B)
        if prev_s is not None:
            acc.add_term(prev_s, 1)
            acc.add_const(-offset)
        if k == num_cols - 1:
            # final carry must be zero
            acc.enforce_zero()
            break
        # - 2^16·carry_out, carry_out = s - 2^B
        s = cs.alloc_variable_without_value()

        def resolve(vals, terms=list(acc.items), const=acc.const):
            total = const % gl.P
            for (var, coeff), v in zip(terms, vals):
                total = (total + coeff * v) % gl.P
            # interpret as signed small integer around 0
            if total > gl.P // 2:
                total -= gl.P
            assert total % LIMB == 0, "congruence column not divisible"
            return [(total // LIMB + offset) % gl.P]

        cs.set_values_with_dependencies(
            [v for v, _ in acc.items], [s], resolve
        )
        decompose_and_check(cs, s, CARRY_CHECK_BITS)
        acc.add_term(s, -(LIMB))
        acc.add_const(LIMB * offset)
        acc.enforce_zero()
        prev_s = s


class NonNativeField:
    """A foreign-field element as 16-bit limb variables."""

    __slots__ = ("limbs", "params")

    def __init__(self, limbs, params: NNFParams):
        assert len(limbs) == params.num_limbs
        self.limbs = list(limbs)
        self.params = params

    # -- allocation ---------------------------------------------------------

    @classmethod
    def allocate_checked(cls, cs, value: int, params: NNFParams):
        assert 0 <= value < params.modulus
        limbs = []
        for lv in _limbs_of(value, params.num_limbs):
            v = cs.alloc_variable_with_value(lv)
            decompose_and_check(cs, v, LIMB_BITS)
            limbs.append(v)
        return cls(limbs, params)

    @classmethod
    def allocated_constant(cls, cs, value: int, params: NNFParams):
        assert 0 <= value < (1 << (LIMB_BITS * params.num_limbs))
        return cls(
            [cs.allocate_constant(lv) for lv in _limbs_of(value, params.num_limbs)],
            params,
        )

    @classmethod
    def zero(cls, cs, params: NNFParams):
        return cls.allocated_constant(cs, 0, params)

    @classmethod
    def one(cls, cs, params: NNFParams):
        return cls.allocated_constant(cs, 1, params)

    def get_value(self, cs) -> int:
        out = 0
        for i, v in enumerate(self.limbs):
            out |= cs.get_value(v) << (LIMB_BITS * i)
        return out % self.params.modulus

    def get_raw_value(self, cs) -> int:
        out = 0
        for i, v in enumerate(self.limbs):
            out |= cs.get_value(v) << (LIMB_BITS * i)
        return out

    # -- internals ----------------------------------------------------------

    def _alloc_result(self, cs, value: int, num_q: int, q_value: int):
        """Fresh 16-bit-checked r limbs for `value` and q limbs for q_value."""
        p = self.params
        assert 0 <= q_value < (1 << (LIMB_BITS * num_q)), "quotient overflow"
        r_limbs = []
        for lv in _limbs_of(value, p.num_limbs):
            v = cs.alloc_variable_with_value(lv)
            decompose_and_check(cs, v, LIMB_BITS)
            r_limbs.append(v)
        q_limbs = []
        for lv in _limbs_of(q_value, num_q):
            v = cs.alloc_variable_with_value(lv)
            decompose_and_check(cs, v, LIMB_BITS)
            q_limbs.append(v)
        return r_limbs, q_limbs

    # -- ring ops -----------------------------------------------------------

    def add(self, cs, other: "NonNativeField") -> "NonNativeField":
        p = self.params
        a = self.get_raw_value(cs)
        b = other.get_raw_value(cs)
        total = a + b
        q, r = divmod(total, p.modulus)
        r_limbs, q_limbs = self._alloc_result(cs, r, 2, q)
        columns = [
            ([(self.limbs[k], 1), (other.limbs[k], 1)], 0)
            for k in range(p.num_limbs)
        ]
        _enforce_congruence(cs, columns, q_limbs, r_limbs, p)
        return NonNativeField(r_limbs, p)

    def sub(self, cs, other: "NonNativeField") -> "NonNativeField":
        """a - b ≡ a + (K·m)_digits - b with K·m pre-redistributed so every
        column stays nonnegative."""
        p = self.params
        n = p.num_limbs
        # digits of 2·m with d_k >= 2^16 - 1 for k < top (host-side borrow)
        K = 2
        d = _limbs_of(K * p.modulus, n + 1)
        for k in range(n):
            if d[k] < LIMB - 1:
                d[k] += LIMB
                d[k + 1] -= 1
        assert all(x >= 0 for x in d)
        a = self.get_raw_value(cs)
        b = other.get_raw_value(cs)
        total = a + K * p.modulus - b
        q, r = divmod(total, p.modulus)
        r_limbs, q_limbs = self._alloc_result(cs, r, 2, q)
        columns = []
        for k in range(n + 1):
            terms = []
            if k < n:
                terms.append((self.limbs[k], 1))
                terms.append((other.limbs[k], -1 + gl.P))
            columns.append((terms, d[k]))
        _enforce_congruence(cs, columns, q_limbs, r_limbs, p)
        return NonNativeField(r_limbs, p)

    def negated(self, cs) -> "NonNativeField":
        return NonNativeField.zero(cs, self.params).sub(cs, self)

    def mul(self, cs, other: "NonNativeField") -> "NonNativeField":
        p = self.params
        n = p.num_limbs
        a = self.get_raw_value(cs)
        b = other.get_raw_value(cs)
        q, r = divmod(a * b, p.modulus)
        r_limbs, q_limbs = self._alloc_result(cs, r, n + 1, q)
        # product variables per (i, j), grouped into columns
        columns = [([], 0) for _ in range(2 * n - 1)]
        for i in range(n):
            for j in range(n):
                pv = FmaGate.fma(
                    cs, self.limbs[i], other.limbs[j], cs.zero_var(), 1, 0
                )
                columns[i + j][0].append((pv, 1))
        _enforce_congruence(cs, columns, q_limbs, r_limbs, p)
        return NonNativeField(r_limbs, p)

    def square(self, cs) -> "NonNativeField":
        return self.mul(cs, self)

    def inv(self, cs) -> "NonNativeField":
        """Witness inverse with self·inv ≡ 1 (mod m) enforced. Input must be
        nonzero mod m."""
        p = self.params
        n = p.num_limbs
        a = self.get_raw_value(cs) % p.modulus
        iv = pow(a, -1, p.modulus)
        iv_limbs = []
        for lv in _limbs_of(iv, n):
            v = cs.alloc_variable_with_value(lv)
            decompose_and_check(cs, v, LIMB_BITS)
            iv_limbs.append(v)
        inv_el = NonNativeField(iv_limbs, p)
        q = (self.get_raw_value(cs) * iv - 1) // p.modulus
        q_limbs = []
        for lv in _limbs_of(q, n + 1):
            v = cs.alloc_variable_with_value(lv)
            decompose_and_check(cs, v, LIMB_BITS)
            q_limbs.append(v)
        one_limbs = [cs.one_var()] + [cs.zero_var()] * (n - 1)
        columns = [([], 0) for _ in range(2 * n - 1)]
        for i in range(n):
            for j in range(n):
                pv = FmaGate.fma(
                    cs, self.limbs[i], iv_limbs[j], cs.zero_var(), 1, 0
                )
                columns[i + j][0].append((pv, 1))
        _enforce_congruence(cs, columns, q_limbs, one_limbs, p)
        return inv_el

    def div(self, cs, other: "NonNativeField") -> "NonNativeField":
        return self.mul(cs, other.inv(cs))

    # -- canonicity / predicates -------------------------------------------

    def enforce_reduced(self, cs):
        """Enforce raw value < m: (m-1) - self has no borrow — a u16 sub
        chain whose final borrow is pinned to zero."""
        p = self.params
        n = p.num_limbs
        m1 = _limbs_of(p.modulus - 1, n)
        raw = self.get_raw_value(cs)
        assert raw < p.modulus, "witness not reduced"
        d = p.modulus - 1 - raw
        gate = UIntXAddGate(16)
        carry = cs.zero_var()
        for k in range(n):
            dv = cs.alloc_variable_with_value((d >> (16 * k)) & (LIMB - 1))
            decompose_and_check(cs, dv, LIMB_BITS)
            cout = (
                cs.alloc_variable_with_value(
                    1
                    if (raw & ((1 << (16 * (k + 1))) - 1))
                    + (d & ((1 << (16 * (k + 1))) - 1))
                    >= (1 << (16 * (k + 1)))
                    else 0
                )
                if k + 1 < n
                else cs.zero_var()
            )
            m1_var = cs.allocate_constant(m1[k])
            cs.place_gate(
                gate, [self.limbs[k], dv, carry, m1_var, cout], ()
            )
            carry = cout

    @staticmethod
    def equals(cs, a: "NonNativeField", b: "NonNativeField") -> Boolean:
        """Canonical equality: both sides reduced, then limbwise compare."""
        a.enforce_reduced(cs)
        b.enforce_reduced(cs)
        flags = [
            Num(la).equals(cs, Num(lb))
            for la, lb in zip(a.limbs, b.limbs)
        ]
        return Boolean.multi_and(cs, flags)

    def is_zero(self, cs) -> Boolean:
        self.enforce_reduced(cs)
        total = Num.linear_combination(
            cs, [Num(v) for v in self.limbs], [1] * self.params.num_limbs
        )
        return total.is_zero(cs)

    @staticmethod
    def select(cs, flag: Boolean, a: "NonNativeField", b: "NonNativeField"):
        assert a.params is b.params
        from ..cs.gates.simple import SelectionGate

        limbs = [
            SelectionGate.select(cs, flag.var, la, lb)
            for la, lb in zip(a.limbs, b.limbs)
        ]
        return NonNativeField(limbs, a.params)


# Common parameter presets (reference uses secp256k1 for ECRecover circuits)
SECP256K1_BASE = NNFParams(
    (1 << 256) - (1 << 32) - 977, "secp256k1_base"
)
SECP256K1_SCALAR = NNFParams(
    0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
    "secp256k1_scalar",
)
BN254_BASE = NNFParams(
    21888242871839275222246405745257275088696311157297823662689037894645226208583,
    "bn254_base",
)
