"""Legacy-Poseidon circuit round function + sponge gadget.

Counterpart of `/root/reference/src/gadgets/poseidon/mod.rs` (the circuit
round function delegating to the legacy flattened gate,
`src/cs/gates/poseidon.rs:1249`) and the generic algebraic sponge
(`/root/reference/src/algebraic_props/sponge.rs`) instantiated over circuit
variables: rate 8 / capacity 4 / overwrite mode, bit-compatible with the
host legacy permutation (`boojum_tpu.hashes.poseidon`) — a recursion circuit
using this sponge recomputes exactly the challenges a
`ProofConfig(transcript="poseidon")` prover drew.
"""

from __future__ import annotations

from ..cs.gates.poseidon_flat import SW, PoseidonFlattenedGate

RATE = 8
CAPACITY = 4


def circuit_permutation(cs, state_vars):
    """One width-12 legacy-Poseidon permutation over circuit variables (one
    flattened-gate instance)."""
    return PoseidonFlattenedGate.permutation(cs, state_vars)


class CircuitPoseidonSponge:
    """Overwrite-mode sponge over circuit variables (reference
    sponge.rs:172 generic sponge instantiated with the legacy round
    function; absorb order matches the host `PoseidonSpongeHost`)."""

    def __init__(self, cs):
        self.cs = cs
        zero = cs.zero_var()
        self.state = [zero] * SW
        self.buffer: list = []

    def absorb(self, variables):
        self.buffer.extend(variables)
        while len(self.buffer) >= RATE:
            chunk, self.buffer = self.buffer[:RATE], self.buffer[RATE:]
            self.state = circuit_permutation(
                self.cs, chunk + self.state[RATE:]
            )

    def finalize(self, n=CAPACITY):
        if self.buffer:
            zero = self.cs.zero_var()
            pad = [zero] * (RATE - len(self.buffer))
            self.state = circuit_permutation(
                self.cs, self.buffer + pad + self.state[RATE:]
            )
            self.buffer = []
        return self.state[:n]


def circuit_hash_leaf(cs, variables, n=CAPACITY):
    sp = CircuitPoseidonSponge(cs)
    sp.absorb(list(variables))
    return sp.finalize(n)


def circuit_hash_node(cs, left, right):
    sp = CircuitPoseidonSponge(cs)
    sp.absorb(list(left) + list(right))
    return sp.finalize(CAPACITY)
