"""Num gadget: a field element as a circuit value (reference
`/root/reference/src/gadgets/num/mod.rs:27`, 1,860 LoC).

Arithmetic lowers to FMA / Reduction gates; equality uses the ZeroCheck gate;
`spread_into_bits` allocates booleans and enforces the binary recomposition.
"""

from __future__ import annotations

from ..cs.gates.simple import (
    BooleanConstraintGate,
    FmaGate,
    ReductionGate,
    SelectionGate,
    ZeroCheckGate,
)
from ..field import gl
from .boolean import Boolean


class Num:
    __slots__ = ("var",)

    def __init__(self, var: int):
        self.var = var

    # -- allocation ---------------------------------------------------------

    @staticmethod
    def allocate(cs, value: int) -> "Num":
        return Num(cs.alloc_variable_with_value(value % gl.P))

    @staticmethod
    def allocated_constant(cs, value: int) -> "Num":
        return Num(cs.allocate_constant(value))

    @staticmethod
    def zero(cs) -> "Num":
        return Num(cs.zero_var())

    @staticmethod
    def one(cs) -> "Num":
        return Num(cs.one_var())

    def get_value(self, cs) -> int:
        return cs.get_value(self.var)

    # -- arithmetic ---------------------------------------------------------

    def add(self, cs, other: "Num") -> "Num":
        return Num(FmaGate.fma(cs, cs.one_var(), self.var, other.var, 1, 1))

    def sub(self, cs, other: "Num") -> "Num":
        return Num(
            FmaGate.fma(cs, cs.one_var(), other.var, self.var, gl.P - 1, 1)
        )

    def mul(self, cs, other: "Num") -> "Num":
        return Num(FmaGate.fma(cs, self.var, other.var, cs.zero_var(), 1, 0))

    def square(self, cs) -> "Num":
        return self.mul(cs, self)

    def mul_by_constant(self, cs, k: int) -> "Num":
        return Num(
            FmaGate.fma(cs, cs.one_var(), self.var, cs.zero_var(), k % gl.P, 0)
        )

    def add_constant(self, cs, k: int) -> "Num":
        return Num(
            FmaGate.fma(cs, cs.one_var(), cs.one_var(), self.var, k % gl.P, 1)
        )

    def fma(self, cs, other: "Num", addend: "Num", c0=1, c1=1) -> "Num":
        return Num(FmaGate.fma(cs, self.var, other.var, addend.var, c0, c1))

    @staticmethod
    def linear_combination(cs, nums, coeffs) -> "Num":
        """Σ coeff_i·num_i via chained Reduction gates."""
        assert len(nums) == len(coeffs) and nums
        acc = None
        items = [(n.var, c % gl.P) for n, c in zip(nums, coeffs)]
        while items:
            chunk, items = items[:3], items[3:]
            vars4 = [v for v, _ in chunk]
            cf = [c for _, c in chunk]
            if acc is not None:
                vars4 = [acc] + vars4
                cf = [1] + cf
            while len(vars4) < 4:
                vars4.append(cs.zero_var())
                cf.append(0)
            acc = ReductionGate.reduce(cs, vars4, cf)
        return Num(acc)

    # -- predicates & control ----------------------------------------------

    def is_zero(self, cs) -> Boolean:
        return Boolean(ZeroCheckGate.is_zero(cs, self.var))

    def equals(self, cs, other: "Num") -> Boolean:
        return self.sub(cs, other).is_zero(cs)

    @staticmethod
    def select(cs, flag: Boolean, a: "Num", b: "Num") -> "Num":
        return Num(SelectionGate.select(cs, flag.var, a.var, b.var))

    def mask(self, cs, flag: Boolean) -> "Num":
        """flag ? self : 0."""
        return Num(FmaGate.fma(cs, self.var, flag.var, cs.zero_var(), 1, 0))

    # -- bit decomposition --------------------------------------------------

    def spread_into_bits(self, cs, num_bits: int) -> list:
        """LE booleans b_i with Σ b_i·2^i = self (reference num/mod.rs
        spread_into_bits)."""
        bits = cs.alloc_multiple_variables_without_values(num_bits)

        def resolve(vals):
            x = vals[0]
            return [(x >> i) & 1 for i in range(num_bits)]

        from ..native import OP_SPLIT

        cs.set_values_with_dependencies(
            [self.var], bits, resolve, native=(OP_SPLIT, (1,))
        )
        for b in bits:
            BooleanConstraintGate.enforce(cs, b)
        from .chunk_utils import enforce_chunk_recomposition

        enforce_chunk_recomposition(cs, bits, self.var, bits_per_chunk=1)
        return [Boolean(b) for b in bits]
