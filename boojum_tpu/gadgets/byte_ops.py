"""Shared byte-level circuit ops for the hash gadgets.

Counterpart of the helpers in
`/root/reference/src/gadgets/blake2s/mixing_function.rs:211` (`xor_many`,
`split_byte_using_table`, `merge_byte_using_table`) and
`/root/reference/src/gadgets/keccak256/round_function.rs` (`rotate_word`):
words are little-endian lists of byte variables; xor/and are 8-bit-table
lookups, rotations split bytes via per-split-point lookup tables and remerge
neighbouring halves with one FMA gate per output byte.
"""

from __future__ import annotations

from ..cs.gates.simple import FmaGate
from ..cs.lookup_table import and8_table, xor8_table
from .tables import byte_split_table


def ensure_table(cs, name: str, builder):
    return cs.ensure_table(name, builder)


def ensure_xor8(cs):
    return ensure_table(cs, "xor8", xor8_table)


def ensure_and8(cs):
    return ensure_table(cs, "and8", and8_table)


def ensure_byte_split(cs, split_at: int):
    return ensure_table(
        cs, f"byte_split_at{split_at}", lambda: byte_split_table(split_at)
    )


def xor_many(cs, a_bytes, b_bytes):
    xor_id = cs.get_table_id("xor8")
    return [
        cs.perform_lookup(xor_id, [a, b])[0] for a, b in zip(a_bytes, b_bytes)
    ]


def and_many(cs, a_bytes, b_bytes):
    and_id = cs.get_table_id("and8")
    return [
        cs.perform_lookup(and_id, [a, b])[0] for a, b in zip(a_bytes, b_bytes)
    ]


def range_check_byte(cs, v):
    """Force v in [0,256) via xor8 table membership (reference
    range_check_u8_pair, blake2s/mixing_function.rs)."""
    xor_id = cs.get_table_id("xor8")
    cs.perform_lookup(xor_id, [v, cs.zero_var()])


def rotate_bytes_left(cs, word, r: int):
    """Rotate a little-endian byte-variable word left by r bits. The
    byte-aligned part is a free relabeling; the residual shift `rem` splits
    each byte at `8 - rem` via lookup and remerges neighbours with one FMA:
    out[j] = low[(j-k) % nb]·2^rem + high[(j-k-1) % nb]."""
    nb = len(word)
    k, rem = divmod(r % (8 * nb), 8)
    if rem == 0:
        return [word[(j - k) % nb] for j in range(nb)]
    split_id = cs.get_table_id(f"byte_split_at{8 - rem}")
    lows, highs = [], []
    for b in word:
        lo, hi = cs.perform_lookup(split_id, [b])
        lows.append(lo)
        highs.append(hi)
    one = cs.one_var()
    return [
        FmaGate.fma(cs, one, lows[(j - k) % nb], highs[(j - k - 1) % nb],
                    1 << rem, 1)
        for j in range(nb)
    ]


def rotate_bytes_right(cs, word, r: int):
    return rotate_bytes_left(cs, word, 8 * len(word) - (r % (8 * len(word))))
