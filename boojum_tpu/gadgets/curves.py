"""Short-Weierstrass curve gadgets over non-native fields.

Counterpart of `/root/reference/src/gadgets/curves/` (sw_projective +
zeroable_affine, 596 LoC): projective points with the complete
addition/doubling formulas of Renes–Costello (eprint 2015/1060, same source
the reference cites) specialized to a = 0 curves (secp256k1, BN254), plus a
flagged affine wrapper for inputs that may be the identity.
"""

from __future__ import annotations

from .boolean import Boolean
from .non_native_field import NNFParams, NonNativeField


class SWProjectivePoint:
    """(X : Y : Z) on y² = x³ + b, a = 0 (reference sw_projective/mod.rs)."""

    __slots__ = ("x", "y", "z", "params", "curve_b")

    def __init__(self, x, y, z, curve_b: int):
        self.x = x
        self.y = y
        self.z = z
        self.params = x.params
        self.curve_b = curve_b

    @classmethod
    def from_xy_unchecked(cls, cs, x: NonNativeField, y: NonNativeField, curve_b: int):
        z = NonNativeField.one(cs, x.params)
        return cls(x, y, z, curve_b)

    @classmethod
    def zero(cls, cs, params: NNFParams, curve_b: int):
        """The identity (0 : 1 : 0)."""
        return cls(
            NonNativeField.zero(cs, params),
            NonNativeField.one(cs, params),
            NonNativeField.zero(cs, params),
            curve_b,
        )

    def negated(self, cs) -> "SWProjectivePoint":
        return SWProjectivePoint(
            self.x, self.y.negated(cs), self.z, self.curve_b
        )

    def double(self, cs) -> "SWProjectivePoint":
        """Complete doubling, a = 0 (2015/1060 algorithm 9)."""
        x, y, z = self.x, self.y, self.z
        b3 = NonNativeField.allocated_constant(
            cs, (3 * self.curve_b) % self.params.modulus, self.params
        )
        t0 = y.square(cs)
        z3 = t0.add(cs, t0)
        z3 = z3.add(cs, z3)
        z3 = z3.add(cs, z3)
        t1 = y.mul(cs, z)
        t2 = z.square(cs)
        t2 = b3.mul(cs, t2)
        x3 = t2.mul(cs, z3)
        y3 = t0.add(cs, t2)
        z3 = t1.mul(cs, z3)
        t1 = t2.add(cs, t2)
        t2 = t1.add(cs, t2)
        t0 = t0.sub(cs, t2)
        y3 = t0.mul(cs, y3)
        y3 = x3.add(cs, y3)
        t1 = x.mul(cs, y)
        x3 = t0.mul(cs, t1)
        x3 = x3.add(cs, x3)
        return SWProjectivePoint(x3, y3, z3, self.curve_b)

    def add_mixed(self, cs, ax: NonNativeField, ay: NonNativeField):
        """self + (ax, ay) with (ax, ay) a NON-identity affine point
        (2015/1060 algorithm 8, a = 0; reference add_mixed)."""
        x1, y1, z1 = self.x, self.y, self.z
        b3 = NonNativeField.allocated_constant(
            cs, (3 * self.curve_b) % self.params.modulus, self.params
        )
        t0 = x1.mul(cs, ax)
        t1 = y1.mul(cs, ay)
        t3 = ax.add(cs, ay)
        t4 = x1.add(cs, y1)
        t3 = t3.mul(cs, t4)
        t4 = t0.add(cs, t1)
        t3 = t3.sub(cs, t4)
        t4 = ay.mul(cs, z1)
        t4 = t4.add(cs, y1)
        y3 = ax.mul(cs, z1)
        y3 = y3.add(cs, x1)
        x3 = t0.add(cs, t0)
        t0 = x3.add(cs, t0)
        t2 = b3.mul(cs, z1)
        z3 = t1.add(cs, t2)
        t1 = t1.sub(cs, t2)
        y3 = b3.mul(cs, y3)
        x3 = t4.mul(cs, y3)
        t2 = t3.mul(cs, t1)
        x3 = t2.sub(cs, x3)
        y3 = y3.mul(cs, t0)
        t1 = t1.mul(cs, z3)
        y3 = t1.add(cs, y3)
        t0 = t0.mul(cs, t3)
        z3 = z3.mul(cs, t4)
        z3 = z3.add(cs, t0)
        return SWProjectivePoint(x3, y3, z3, self.curve_b)

    def sub_mixed(self, cs, ax: NonNativeField, ay: NonNativeField):
        return self.add_mixed(cs, ax, ay.negated(cs))

    def convert_to_affine_or_default(self, cs, default_x: int, default_y: int):
        """((x, y), at_infinity): affine coordinates via witness z-inverse,
        or the provided default when z = 0 (reference
        convert_to_affine_or_default)."""
        params = self.params
        at_inf = self.z.is_zero(cs)
        # safe_z = z if z != 0 else 1 (so inv() is well-defined)
        one = NonNativeField.one(cs, params)
        safe_z = NonNativeField.select(cs, at_inf, one, self.z)
        z_inv = safe_z.inv(cs)
        x_aff = self.x.mul(cs, z_inv)
        y_aff = self.y.mul(cs, z_inv)
        dx = NonNativeField.allocated_constant(cs, default_x, params)
        dy = NonNativeField.allocated_constant(cs, default_y, params)
        x_out = NonNativeField.select(cs, at_inf, dx, x_aff)
        y_out = NonNativeField.select(cs, at_inf, dy, y_aff)
        return (x_out, y_out), at_inf

    def enforce_on_curve(self, cs):
        """Y²·Z = X³ + b·Z³ (projective curve equation)."""
        params = self.params
        b_c = NonNativeField.allocated_constant(cs, self.curve_b, params)
        lhs = self.y.square(cs).mul(cs, self.z)
        x3 = self.x.square(cs).mul(cs, self.x)
        z3 = self.z.square(cs).mul(cs, self.z)
        rhs = x3.add(cs, b_c.mul(cs, z3))
        diff = lhs.sub(cs, rhs)
        flag = diff.is_zero(cs)
        from ..cs.gates.simple import FmaGate

        FmaGate.enforce_fma(
            cs, cs.one_var(), flag.var, cs.one_var(), cs.one_var(), 1, 0
        )


class ZeroableAffinePoint:
    """Affine point with an explicit is-infinity flag (reference
    curves/zeroable_affine)."""

    __slots__ = ("x", "y", "is_infinity")

    def __init__(self, x: NonNativeField, y: NonNativeField, is_infinity: Boolean):
        self.x = x
        self.y = y
        self.is_infinity = is_infinity
