"""Fiat–Shamir transcript over the Poseidon2 sponge (host-side).

Semantics follow the reference's algebraic sponge transcript
(`/root/reference/src/cs/implementations/transcript.rs:48`
AlgebraicSpongeBasedTranscript, overwrite absorption, rescue-prime padding
with a trailing 1) and its query-index bit buffer (`:369` BoolsBuffer). The
transcript is inherently sequential and tiny, so it runs on host python ints;
everything it absorbs (caps, evaluations) is read back from device once per
round.

Field genericity (ISSUE 19): every p-specific constant — the reduction
modulus, the sponge width/rate, the absorb word width, the extension degree
one `get_ext_challenge` spans — reads from a `field.spec.FieldSpec` class
attribute. The Goldilocks defaults are BIT-IDENTICAL to the hardcoded
originals; `Poseidon2BabyBearTranscript` is the same machine instantiated
at the BabyBear record (width-16 permutation, 31-bit elements, degree-4
ext challenges).
"""

from .field import gl
from .field.spec import BABYBEAR as _BB_SPEC
from .field.spec import GOLDILOCKS as _GL_SPEC
from .hashes.poseidon2 import poseidon2_permutation_host


class Poseidon2Transcript:
    """Algebraic sponge transcript over a width-12 permutation; subclasses
    swap the permutation (the reference is generic over the round function
    the same way, transcript.rs:48) and/or the FieldSpec."""

    _SPEC = _GL_SPEC
    _PERMUTATION = staticmethod(poseidon2_permutation_host)

    def __init__(self):
        self.state = [0] * self._SPEC.sponge_width
        self.buffer = []
        self.available = []

    def witness_field_elements(self, els):
        p = self._SPEC.p
        self.buffer.extend(int(e) % p for e in els)

    def witness_merkle_tree_cap(self, cap):
        for digest in cap:
            self.witness_field_elements(digest)

    def get_challenge(self) -> int:
        rate = self._SPEC.sponge_rate
        if not self.buffer:
            if self.available:
                return self.available.pop(0)
            self.state = self._PERMUTATION(self.state)
            self.available = list(self.state[:rate])
            return self.available.pop(0)
        # rescue-prime padding: trailing 1, then zeros to a multiple of rate
        to_absorb = self.buffer + [1]
        self.buffer = []
        while len(to_absorb) % rate != 0:
            to_absorb.append(0)
        for i in range(0, len(to_absorb), rate):
            self.state[:rate] = to_absorb[i : i + rate]
            self.state = self._PERMUTATION(self.state)
        self.available = list(self.state[:rate])
        return self.available.pop(0)

    def get_multiple_challenges(self, n: int):
        return [self.get_challenge() for _ in range(n)]

    def get_ext_challenge(self):
        """One challenge per extension coordinate — a 2-tuple over
        Goldilocks, a 4-tuple over BabyBear (where 31-bit base draws are
        unsound and all protocol challenges live in GF(p^4))."""
        return tuple(
            self.get_challenge() for _ in range(self._SPEC.ext_degree)
        )


class _ByteTranscript:
    """Byte-oriented transcript base (reference Blake2sTranscript /
    Keccak256Transcript, transcript.rs:155,264): field elements are absorbed
    as `elem_bytes`-wide LE words (8 for Goldilocks); on each challenge
    request the pending buffer is folded into a running 32-byte seed, then
    challenges are squeezed as `hash(seed ‖ counter_le4)` blocks, each LE
    word reduced mod p."""

    _SPEC = _GL_SPEC

    def __init__(self):
        self.seed = b"\x00" * 32
        self.buffer = bytearray()
        self.counter = 0
        self.available = []

    def _hash(self, data: bytes) -> bytes:
        raise NotImplementedError

    def witness_field_elements(self, els):
        p = self._SPEC.p
        width = self._SPEC.elem_bytes
        for e in els:
            self.buffer += (int(e) % p).to_bytes(width, "little")

    def witness_merkle_tree_cap(self, cap):
        for digest in cap:
            self.witness_field_elements(digest)

    def get_challenge(self) -> int:
        p = self._SPEC.p
        width = self._SPEC.elem_bytes
        if self.buffer:
            self.seed = self._hash(self.seed + bytes(self.buffer))
            self.buffer = bytearray()
            self.counter = 0
            self.available = []
        if not self.available:
            block = self._hash(
                self.seed + self.counter.to_bytes(4, "little")
            )
            self.counter += 1
            self.available = [
                int.from_bytes(block[i : i + width], "little") % p
                for i in range(0, 32, width)
            ]
        return self.available.pop(0)

    def get_multiple_challenges(self, n: int):
        return [self.get_challenge() for _ in range(n)]

    def get_ext_challenge(self):
        return tuple(
            self.get_challenge() for _ in range(self._SPEC.ext_degree)
        )


class Blake2sTranscript(_ByteTranscript):
    def _hash(self, data: bytes) -> bytes:
        import hashlib

        return hashlib.blake2s(data).digest()


class Keccak256Transcript(_ByteTranscript):
    def _hash(self, data: bytes) -> bytes:
        from .hashes.keccak_host import keccak256

        return keccak256(data)


from .hashes.poseidon import poseidon_permutation_host as _poseidon_perm


class PoseidonTranscript(Poseidon2Transcript):
    """Same sponge semantics over the LEGACY Poseidon permutation
    (reference GoldilocksPoisedonTranscript, transcript.rs:48 with the
    original round function)."""

    _PERMUTATION = staticmethod(_poseidon_perm)


def _bb_permutation_host(state):
    # lazy: hashes/poseidon2_bb drags in jax; the Goldilocks transcripts
    # must stay importable without paying for the BabyBear backend
    from .hashes.poseidon2_bb import poseidon2_permutation_bb_host

    return poseidon2_permutation_bb_host(state)


class Poseidon2BabyBearTranscript(Poseidon2Transcript):
    """The BabyBear instantiation: width-16 permutation over p = 2^31 -
    2^27 + 1, rate 8, degree-4 ext challenges (field/spec.py BABYBEAR)."""

    _SPEC = _BB_SPEC
    _PERMUTATION = staticmethod(_bb_permutation_host)


class Blake2sBabyBearTranscript(Blake2sTranscript):
    """Byte transcript at the BabyBear record: 4-byte LE absorb words,
    8 challenge words per squeezed 32-byte block."""

    _SPEC = _BB_SPEC


TRANSCRIPTS = {
    "poseidon2": Poseidon2Transcript,
    "poseidon": PoseidonTranscript,
    "blake2s": Blake2sTranscript,
    "keccak256": Keccak256Transcript,
    "poseidon2_babybear": Poseidon2BabyBearTranscript,
    "blake2s_babybear": Blake2sBabyBearTranscript,
}


def make_transcript(kind: str = "poseidon2"):
    return TRANSCRIPTS[kind]()


class BitSource:
    """Uniform query-index bits drawn from transcript challenges.

    Takes only the low (challenge_bits - max_needed) bits of each
    challenge for uniformity, as the reference does (`transcript.rs:388`).
    `challenge_bits` is the field's challenge word width — 64 for
    Goldilocks (the historical hardcode), 31 for BabyBear
    (FieldSpec.challenge_bits).
    """

    def __init__(self, max_needed_bits: int, challenge_bits: int = 64):
        assert 0 < max_needed_bits < challenge_bits
        self.bits = []
        self.max_needed = max_needed_bits
        self.challenge_bits = challenge_bits

    def get_bits(self, transcript: Poseidon2Transcript, num_bits: int):
        while len(self.bits) < num_bits:
            c = transcript.get_challenge()
            usable = self.challenge_bits - self.max_needed
            self.bits.extend((c >> i) & 1 for i in range(usable))
        out, self.bits = self.bits[:num_bits], self.bits[num_bits:]
        return out

    def get_index(self, transcript: Poseidon2Transcript, num_bits: int) -> int:
        bits = self.get_bits(transcript, num_bits)
        idx = 0
        for i, b in enumerate(bits):
            idx |= b << i
        return idx
