"""Fiat–Shamir transcript over the Poseidon2 sponge (host-side).

Semantics follow the reference's algebraic sponge transcript
(`/root/reference/src/cs/implementations/transcript.rs:48`
AlgebraicSpongeBasedTranscript, overwrite absorption, rescue-prime padding
with a trailing 1) and its query-index bit buffer (`:369` BoolsBuffer). The
transcript is inherently sequential and tiny, so it runs on host python ints;
everything it absorbs (caps, evaluations) is read back from device once per
round.
"""

from .field import gl
from .hashes.poseidon2 import poseidon2_permutation_host


class Poseidon2Transcript:
    """Algebraic sponge transcript over a width-12 permutation; subclasses
    swap the permutation (the reference is generic over the round function
    the same way, transcript.rs:48)."""

    _PERMUTATION = staticmethod(poseidon2_permutation_host)

    def __init__(self):
        self.state = [0] * 12
        self.buffer = []
        self.available = []

    def witness_field_elements(self, els):
        self.buffer.extend(int(e) % gl.P for e in els)

    def witness_merkle_tree_cap(self, cap):
        for digest in cap:
            self.witness_field_elements(digest)

    def get_challenge(self) -> int:
        if not self.buffer:
            if self.available:
                return self.available.pop(0)
            self.state = self._PERMUTATION(self.state)
            self.available = list(self.state[:8])
            return self.available.pop(0)
        # rescue-prime padding: trailing 1, then zeros to a multiple of rate
        to_absorb = self.buffer + [1]
        self.buffer = []
        while len(to_absorb) % 8 != 0:
            to_absorb.append(0)
        for i in range(0, len(to_absorb), 8):
            self.state[:8] = to_absorb[i : i + 8]
            self.state = self._PERMUTATION(self.state)
        self.available = list(self.state[:8])
        return self.available.pop(0)

    def get_multiple_challenges(self, n: int):
        return [self.get_challenge() for _ in range(n)]

    def get_ext_challenge(self):
        c0 = self.get_challenge()
        c1 = self.get_challenge()
        return (c0, c1)


class _ByteTranscript:
    """Byte-oriented transcript base (reference Blake2sTranscript /
    Keccak256Transcript, transcript.rs:155,264): field elements are absorbed
    as 8-byte LE words; on each challenge request the pending buffer is
    folded into a running 32-byte seed, then challenges are squeezed as
    `hash(seed ‖ counter_le4)` blocks, each 8-byte LE word reduced mod p."""

    def __init__(self):
        self.seed = b"\x00" * 32
        self.buffer = bytearray()
        self.counter = 0
        self.available = []

    def _hash(self, data: bytes) -> bytes:
        raise NotImplementedError

    def witness_field_elements(self, els):
        for e in els:
            self.buffer += (int(e) % gl.P).to_bytes(8, "little")

    def witness_merkle_tree_cap(self, cap):
        for digest in cap:
            self.witness_field_elements(digest)

    def get_challenge(self) -> int:
        if self.buffer:
            self.seed = self._hash(self.seed + bytes(self.buffer))
            self.buffer = bytearray()
            self.counter = 0
            self.available = []
        if not self.available:
            block = self._hash(
                self.seed + self.counter.to_bytes(4, "little")
            )
            self.counter += 1
            self.available = [
                int.from_bytes(block[i : i + 8], "little") % gl.P
                for i in range(0, 32, 8)
            ]
        return self.available.pop(0)

    def get_multiple_challenges(self, n: int):
        return [self.get_challenge() for _ in range(n)]

    def get_ext_challenge(self):
        return (self.get_challenge(), self.get_challenge())


class Blake2sTranscript(_ByteTranscript):
    def _hash(self, data: bytes) -> bytes:
        import hashlib

        return hashlib.blake2s(data).digest()


class Keccak256Transcript(_ByteTranscript):
    def _hash(self, data: bytes) -> bytes:
        from .hashes.keccak_host import keccak256

        return keccak256(data)


from .hashes.poseidon import poseidon_permutation_host as _poseidon_perm


class PoseidonTranscript(Poseidon2Transcript):
    """Same sponge semantics over the LEGACY Poseidon permutation
    (reference GoldilocksPoisedonTranscript, transcript.rs:48 with the
    original round function)."""

    _PERMUTATION = staticmethod(_poseidon_perm)


TRANSCRIPTS = {
    "poseidon2": Poseidon2Transcript,
    "poseidon": PoseidonTranscript,
    "blake2s": Blake2sTranscript,
    "keccak256": Keccak256Transcript,
}


def make_transcript(kind: str = "poseidon2"):
    return TRANSCRIPTS[kind]()


class BitSource:
    """Uniform query-index bits drawn from transcript challenges.

    Takes only the low (64 - max_needed) bits of each challenge for
    uniformity, as the reference does (`transcript.rs:388`).
    """

    def __init__(self, max_needed_bits: int):
        assert 0 < max_needed_bits < 64
        self.bits = []
        self.max_needed = max_needed_bits

    def get_bits(self, transcript: Poseidon2Transcript, num_bits: int):
        while len(self.bits) < num_bits:
            c = transcript.get_challenge()
            usable = 64 - self.max_needed
            self.bits.extend((c >> i) & 1 for i in range(usable))
        out, self.bits = self.bits[:num_bits], self.bits[num_bits:]
        return out

    def get_index(self, transcript: Poseidon2Transcript, num_bits: int) -> int:
        bits = self.get_bits(transcript, num_bits)
        idx = 0
        for i, b in enumerate(bits):
            idx |= b << i
        return idx
