// Native witness-resolution tape engine.
//
// Counterpart of the reference's witness DAG resolver execution layer
// (/root/reference/src/dag/resolvers/mt/resolution_window.rs — worker
// threads running closure batches over a value arena; see also the
// ResolverBox closure arena, src/dag/resolver_box.rs). The TPU-framework
// host design records a *typed op tape* during synthesis instead of boxed
// closures: each high-volume gadget resolution (FMA, reductions, chunk
// splits, u32 carry ops, lookups, whole Poseidon2 permutations) is one tape
// entry, and Python flushes the tape through this C engine in batches.
// Python closures remain the general fallback for anything untyped.
//
// Field: Goldilocks p = 2^64 - 2^32 + 1. All values canonical (< p).
//
// Build: g++ -O2 -shared -fPIC -o libboojum_resolver.so resolver.cpp

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

using u64 = uint64_t;
using u32 = uint32_t;
using i64 = int64_t;
using u128 = unsigned __int128;

static const u64 P = 0xFFFFFFFF00000001ull;
static const u64 EPS = 0xFFFFFFFFull; // 2^64 mod p

static inline u64 mod_add(u64 a, u64 b) {
  u64 s = a + b;
  // a,b < p so s wraps at most once; wrapped value is s - 2^64 = s + EPS - p...
  // canonical fixup: if overflow or s >= p, subtract p.
  if (s < a) s += EPS; // s = a + b - 2^64 ; + EPS == a + b - p
  if (s >= P) s -= P;
  return s;
}

static inline u64 mod_sub(u64 a, u64 b) {
  return (a >= b) ? (a - b) : (a + (P - b));
}

static inline u64 mod_mul(u64 a, u64 b) {
  u128 w = (u128)a * (u128)b;
  u64 lo = (u64)w;
  u64 hi = (u64)(w >> 64);
  u64 hi_hi = hi >> 32;
  u64 hi_lo = hi & 0xFFFFFFFFull;
  u64 t0 = lo - hi_hi;
  if (lo < hi_hi) t0 -= EPS; // borrow
  u64 t1 = hi_lo * EPS;
  u64 t2 = t0 + t1;
  if (t2 < t0) t2 += EPS;
  if (t2 >= P) t2 -= P;
  return t2;
}

// ---------------------------------------------------------------------------
// Lookup tables
// ---------------------------------------------------------------------------

struct Table {
  int width = 0;
  int num_keys = 0;
  i64 rows = 0;
  std::vector<u64> content;              // rows * width
  std::unordered_map<u64, i64> index;    // hashed key -> row
  std::vector<u32> multiplicities;       // per row
};

static std::vector<Table> g_tables; // id - 1 indexes

static inline u64 key_hash(const u64 *key, int num_keys) {
  // FNV-1a style over the key words; collisions resolved by verify below
  u64 h = 1469598103934665603ull;
  for (int i = 0; i < num_keys; i++) {
    h ^= key[i];
    h *= 1099511628211ull;
  }
  return h;
}

extern "C" int register_table(i64 table_id, const u64 *content, i64 rows,
                              int width, int num_keys) {
  if (table_id < 1) return -1;
  if (num_keys < 0 || num_keys > 8 || width < num_keys || width > 16)
    return -2; // key buffer in the op interpreter is u64[8]
  if ((i64)g_tables.size() < table_id) g_tables.resize(table_id);
  Table &t = g_tables[table_id - 1];
  t.width = width;
  t.num_keys = num_keys;
  t.rows = rows;
  t.content.assign(content, content + rows * width);
  t.index.clear();
  t.index.reserve(rows * 2);
  t.multiplicities.assign(rows, 0);
  for (i64 r = 0; r < rows; r++) {
    u64 h = key_hash(content + r * width, num_keys);
    // assume distinct keys (asserted python-side at table construction)
    t.index.emplace(h, r);
  }
  return 0;
}

static inline i64 table_find(const Table &t, const u64 *key) {
  u64 h = key_hash(key, t.num_keys);
  auto it = t.index.find(h);
  if (it == t.index.end()) return -1;
  i64 r = it->second;
  for (int j = 0; j < t.num_keys; j++)
    if (t.content[r * t.width + j] != key[j]) return -1;
  return r;
}

extern "C" const u32 *table_multiplicities(i64 table_id, i64 *rows_out) {
  Table &t = g_tables[table_id - 1];
  *rows_out = t.rows;
  return t.multiplicities.data();
}

extern "C" void reset_tables() { g_tables.clear(); }

// ---------------------------------------------------------------------------
// Poseidon2 (width 12, x^7) — constants registered from Python
// ---------------------------------------------------------------------------

static u64 g_rc[30][12];
static u64 g_diag[12];
static bool g_p2_ready = false;

extern "C" void register_poseidon2(const u64 *rc /*30*12*/, const u64 *diag) {
  std::memcpy(g_rc, rc, sizeof(g_rc));
  std::memcpy(g_diag, diag, sizeof(g_diag));
  g_p2_ready = true;
}

static inline u64 pow7(u64 x) {
  u64 x2 = mod_mul(x, x);
  u64 x3 = mod_mul(x2, x);
  return mod_mul(mod_mul(x2, x2), x3);
}

static void ext_mds(u64 *s) {
  // circ(2*M4, M4, M4) via the add/double chain
  u64 blocks[3][4];
  for (int b = 0; b < 3; b++) {
    u64 x0 = s[4 * b], x1 = s[4 * b + 1], x2 = s[4 * b + 2], x3 = s[4 * b + 3];
    u64 t0 = mod_add(x0, x1);
    u64 t1 = mod_add(x2, x3);
    u64 t2 = mod_add(mod_add(x1, x1), t1);
    u64 t3 = mod_add(mod_add(x3, x3), t0);
    u64 t4 = mod_add(mod_add(mod_add(t1, t1), mod_add(t1, t1)), t3);
    u64 t5 = mod_add(mod_add(mod_add(t0, t0), mod_add(t0, t0)), t2);
    blocks[b][0] = mod_add(t3, t5);
    blocks[b][1] = t5;
    blocks[b][2] = mod_add(t2, t4);
    blocks[b][3] = t4;
  }
  u64 sums[4];
  for (int i = 0; i < 4; i++)
    sums[i] = mod_add(mod_add(blocks[0][i], blocks[1][i]), blocks[2][i]);
  for (int b = 0; b < 3; b++)
    for (int i = 0; i < 4; i++) s[4 * b + i] = mod_add(blocks[b][i], sums[i]);
}

static void int_mds(u64 *s) {
  u64 total = 0;
  for (int i = 0; i < 12; i++) total = mod_add(total, s[i]);
  for (int i = 0; i < 12; i++)
    s[i] = mod_add(mod_mul(s[i], g_diag[i]), total);
}

// Full flat permutation trace: outs[0..12) final state, aux[0..106) the
// degree-reset values, in the same order as
// boojum_tpu/cs/gates/poseidon2_flat.py::flat_permutation.
static void poseidon2_flat(const u64 *in, u64 *out12, u64 *aux106) {
  u64 s[12];
  std::memcpy(s, in, sizeof(s));
  int ax = 0;
  ext_mds(s);
  for (int r = 0; r < 4; r++) {
    if (r != 0)
      for (int i = 0; i < 12; i++) aux106[ax++] = s[i];
    for (int i = 0; i < 12; i++) s[i] = pow7(mod_add(s[i], g_rc[r][i]));
    ext_mds(s);
  }
  for (int p = 0; p < 22; p++) {
    u64 s0 = mod_add(s[0], g_rc[4 + p][0]);
    aux106[ax++] = s0;
    s[0] = pow7(s0);
    int_mds(s);
  }
  for (int r = 0; r < 4; r++) {
    for (int i = 0; i < 12; i++) aux106[ax++] = s[i];
    for (int i = 0; i < 12; i++) s[i] = pow7(mod_add(s[i], g_rc[26 + r][i]));
    ext_mds(s);
  }
  std::memcpy(out12, s, sizeof(s));
}

// ---------------------------------------------------------------------------
// Tape execution
// ---------------------------------------------------------------------------

enum OpKind : i64 {
  OP_CONST = 0,
  OP_FMA = 1,         // params c0, c1; ins a, b, c; out d = c0*a*b + c1*c
  OP_REDUCTION = 2,   // params coeffs[k]; ins k; out = sum c_i v_i
  OP_SPLIT = 3,       // params bits, count; in x; outs chunks LE
  OP_U32_ADD = 4,     // params shift_bits; ins a, b, cin; outs c, cout
  OP_U32_SUB = 5,     // ins a, b, bin; outs c, bout
  OP_TRIADD = 6,      // ins a, b, c; outs low, high
  OP_U32_FMA = 7,     // ins a,b,c,cin; outs alo,ahi,blo,bhi,low,high,k
  OP_BYTE_TRIADD = 8, // ins 12 bytes; outs 4 bytes + carry
  OP_POSEIDON2 = 9,   // ins 12; outs 12 + 106
  OP_LOOKUP = 10,     // params table_id; ins num_keys; outs num_values (read-only)
  OP_LOOKUP_BUMP = 11 // params table_id; ins width (full tuple); no outs; owns the multiplicity counter
};

// Executes ops [0, n_ops). Returns 0 on success, or 1-based index of the
// failing op (lookup miss / bad table) negated.
extern "C" i64 execute_tape(
    u64 *values, u64 /*arena_len*/,
    const i64 *kinds, i64 n_ops,
    const u64 *params, const i64 *param_off,
    const i64 *in_places, const i64 *in_off,
    const i64 *out_places, const i64 *out_off) {
  for (i64 op = 0; op < n_ops; op++) {
    const u64 *pp = params + param_off[op];
    const i64 *ins = in_places + in_off[op];
    const i64 n_in = in_off[op + 1] - in_off[op];
    const i64 *outs = out_places + out_off[op];
    const i64 n_out = out_off[op + 1] - out_off[op];
    switch (kinds[op]) {
      case OP_CONST:
        values[outs[0]] = pp[0];
        break;
      case OP_FMA: {
        u64 a = values[ins[0]], b = values[ins[1]], c = values[ins[2]];
        values[outs[0]] = mod_add(mod_mul(pp[0], mod_mul(a, b)),
                                  mod_mul(pp[1], c));
        break;
      }
      case OP_REDUCTION: {
        u64 acc = 0;
        for (i64 j = 0; j < n_in; j++)
          acc = mod_add(acc, mod_mul(pp[j], values[ins[j]]));
        values[outs[0]] = acc;
        break;
      }
      case OP_SPLIT: {
        u64 x = values[ins[0]];
        u64 bits = pp[0];
        u64 mask = (bits >= 64) ? ~0ull : ((1ull << bits) - 1);
        for (i64 j = 0; j < n_out; j++) {
          values[outs[j]] = x & mask;
          x >>= bits;
        }
        break;
      }
      case OP_U32_ADD: {
        u64 s = values[ins[0]] + values[ins[1]] + values[ins[2]];
        u64 w = pp[0];
        values[outs[0]] = s & ((1ull << w) - 1);
        values[outs[1]] = s >> w;
        break;
      }
      case OP_U32_SUB: {
        i64 d = (i64)values[ins[0]] - (i64)values[ins[1]] - (i64)values[ins[2]];
        if (d < 0) {
          values[outs[0]] = (u64)(d + (1ll << 32));
          values[outs[1]] = 1;
        } else {
          values[outs[0]] = (u64)d;
          values[outs[1]] = 0;
        }
        break;
      }
      case OP_TRIADD: {
        u64 s = values[ins[0]] + values[ins[1]] + values[ins[2]];
        values[outs[0]] = s & 0xFFFFFFFFull;
        values[outs[1]] = s >> 32;
        break;
      }
      case OP_U32_FMA: {
        u64 a = values[ins[0]], b = values[ins[1]];
        u64 c = values[ins[2]], cin = values[ins[3]];
        u64 s = a * b + c + cin; // < 2^64, no overflow for u32 operands
        u64 alo = a & 0xFFFF, ahi = a >> 16;
        u64 blo = b & 0xFFFF, bhi = b >> 16;
        u64 part = alo * blo + c + cin + ((alo * bhi + ahi * blo) << 16);
        values[outs[0]] = alo;
        values[outs[1]] = ahi;
        values[outs[2]] = blo;
        values[outs[3]] = bhi;
        values[outs[4]] = s & 0xFFFFFFFFull;
        values[outs[5]] = s >> 32;
        values[outs[6]] = part >> 32;
        break;
      }
      case OP_BYTE_TRIADD: {
        u64 s = 0;
        for (int g = 0; g < 3; g++)
          for (int j = 0; j < 4; j++)
            s += values[ins[4 * g + j]] << (8 * j);
        for (int j = 0; j < 4; j++) values[outs[j]] = (s >> (8 * j)) & 0xFF;
        values[outs[4]] = s >> 32;
        break;
      }
      case OP_POSEIDON2: {
        if (!g_p2_ready) return -(op + 1);
        u64 in[12];
        for (int i = 0; i < 12; i++) in[i] = values[ins[i]];
        u64 out12[12], aux[106];
        poseidon2_flat(in, out12, aux);
        for (int i = 0; i < 12; i++) values[outs[i]] = out12[i];
        for (int i = 0; i < 106; i++) values[outs[12 + i]] = aux[i];
        break;
      }
      case OP_LOOKUP: {
        i64 tid = (i64)pp[0];
        if (tid < 1 || tid > (i64)g_tables.size()) return -(op + 1);
        Table &t = g_tables[tid - 1];
        if (n_in > 8) return -(op + 1);
        u64 key[8];
        for (i64 j = 0; j < n_in; j++) key[j] = values[ins[j]];
        i64 r = table_find(t, key);
        if (r < 0) return -(op + 1);
        for (i64 j = 0; j < n_out; j++)
          values[outs[j]] = t.content[r * t.width + t.num_keys + j];
        break;
      }
      case OP_LOOKUP_BUMP: {
        i64 tid = (i64)pp[0];
        if (tid < 1 || tid > (i64)g_tables.size()) return -(op + 1);
        Table &t = g_tables[tid - 1];
        if (t.num_keys > 8 || (i64)t.num_keys > n_in) return -(op + 1);
        u64 key[8];
        for (int j = 0; j < t.num_keys; j++) key[j] = values[ins[j]];
        i64 r = table_find(t, key);
        if (r < 0) return -(op + 1);
        // verify value part matches (same check as LookupTable.row_index)
        for (int j = t.num_keys; j < t.width && j < (int)n_in; j++)
          if (t.content[r * t.width + j] != values[ins[j]]) return -(op + 1);
        t.multiplicities[r] += 1;
        break;
      }
      default:
        return -(op + 1);
    }
  }
  return 0;
}
