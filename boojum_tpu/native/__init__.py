"""Native (C++) witness-resolution engine: build + ctypes bindings.

Counterpart of the reference's compiled resolver runtime (the Rust
`MtCircuitResolver` machinery, /root/reference/src/dag/). The shared library
is built on demand with g++ and cached next to the source; if no compiler is
available the framework silently falls back to the pure-python resolver.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "resolver.cpp")
_LIB = os.path.join(_HERE, "libboojum_resolver.so")

_lib = None
_tried = False


def _build() -> bool:
    try:
        src_mtime = os.path.getmtime(_SRC)
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= src_mtime:
            return True
        r = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB + ".tmp", _SRC],
            capture_output=True,
            timeout=240,
        )
        if r.returncode != 0:
            return False
        os.replace(_LIB + ".tmp", _LIB)
        return True
    except Exception:
        return False


def get_lib():
    """The loaded library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("BOOJUM_TPU_NO_NATIVE"):
        return None
    if not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        return None
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.register_table.argtypes = [
        ctypes.c_int64, u64p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
    ]
    lib.register_table.restype = ctypes.c_int
    lib.table_multiplicities.argtypes = [ctypes.c_int64, i64p]
    lib.table_multiplicities.restype = u32p
    lib.reset_tables.argtypes = []
    lib.register_poseidon2.argtypes = [u64p, u64p]
    lib.execute_tape.argtypes = [
        u64p, ctypes.c_uint64,
        i64p, ctypes.c_int64,
        u64p, i64p,
        i64p, i64p,
        i64p, i64p,
    ]
    lib.execute_tape.restype = ctypes.c_int64
    # one-time poseidon2 constants
    from ..hashes import poseidon2_params as p2

    rc = np.array(p2.ALL_ROUND_CONSTANTS, dtype=np.uint64)
    diag = np.array(p2.M_I_DIAGONAL, dtype=np.uint64)
    lib.register_poseidon2(
        rc.ctypes.data_as(u64p), diag.ctypes.data_as(u64p)
    )
    _lib = lib
    return _lib


def _as_u64p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _as_i64p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


_next_table_slot = [1]  # process-global: each CS's tables get fresh slots


class NativeTape:
    """Typed-op tape accumulated during synthesis, flushed in batches.

    Local (per-CS) table ids map to process-global C-engine slots so
    multiple constraint systems in one process never share multiplicity
    counters."""

    def __init__(self, lib):
        self.lib = lib
        self.kinds: list[int] = []
        self.params: list[int] = []
        self.param_off: list[int] = [0]
        self.ins: list[int] = []
        self.in_off: list[int] = [0]
        self.outs: list[int] = []
        self.out_off: list[int] = [0]
        self._slot_of: dict[int, int] = {}

    def __len__(self):
        return len(self.kinds)

    def append(self, kind: int, params, ins, outs):
        self.kinds.append(kind)
        self.params.extend(params)
        self.param_off.append(len(self.params))
        self.ins.extend(ins)
        self.in_off.append(len(self.ins))
        self.outs.extend(outs)
        self.out_off.append(len(self.outs))

    def ensure_table(self, table_id: int, table):
        if table_id in self._slot_of:
            return
        slot = _next_table_slot[0]
        _next_table_slot[0] += 1
        content = np.ascontiguousarray(table.content, dtype=np.uint64)
        rc = self.lib.register_table(
            slot, _as_u64p(content), len(content),
            table.width, table.num_keys,
        )
        assert rc == 0
        self._slot_of[table_id] = slot

    def has_table(self, table_id: int) -> bool:
        return table_id in self._slot_of

    def slot_of(self, table_id: int) -> int:
        return self._slot_of[table_id]

    def multiplicities_of(self, table_id: int):
        slot = self._slot_of.get(table_id)
        if slot is None:
            return None
        return self.multiplicities(slot)

    def take_snapshot(self):
        """Detach the accumulated ops as dense arrays (the tape resets).

        Returns None when empty, else an opaque snapshot consumed by
        `run_snapshot` — the split lets a worker thread execute one batch
        while synthesis keeps appending to the (fresh) tape."""
        if not self.kinds:
            return None
        snap = (
            np.array(self.kinds, dtype=np.int64),
            np.array(self.params, dtype=np.uint64),
            np.array(self.param_off, dtype=np.int64),
            np.array(self.ins, dtype=np.int64),
            np.array(self.in_off, dtype=np.int64),
            np.array(self.outs, dtype=np.int64),
            np.array(self.out_off, dtype=np.int64),
            self.outs,
            self.kinds,
        )
        self.kinds = []
        self.params = []
        self.param_off = [0]
        self.ins = []
        self.in_off = [0]
        self.outs = []
        self.out_off = [0]
        return snap

    def run_snapshot(self, values: np.ndarray, snap) -> list:
        """Execute a snapshot against the arena; returns the out places.

        The ctypes call releases the GIL, so running this on a worker
        thread overlaps native resolution with python-side synthesis. A
        failed batch must never be re-executed (ops before the failure
        already ran — a second pass would double-bump lookup
        multiplicities); snapshots are one-shot by construction."""
        kinds, params, p_off, ins, i_off, outs, o_off, out_places, kl = snap
        rc = self.lib.execute_tape(
            _as_u64p(values), len(values),
            _as_i64p(kinds), len(kinds),
            _as_u64p(params), _as_i64p(p_off),
            _as_i64p(ins), _as_i64p(i_off),
            _as_i64p(outs), _as_i64p(o_off),
        )
        if rc != 0:
            raise RuntimeError(
                f"native resolver op (kind {kl[-int(rc) - 1]}) failed — "
                "lookup miss, oversized key, or unregistered table"
            )
        return out_places

    def execute(self, values: np.ndarray) -> list:
        """Run all pending ops against the arena; returns the out places."""
        snap = self.take_snapshot()
        if snap is None:
            return []
        return self.run_snapshot(values, snap)

    def multiplicities(self, table_id: int) -> np.ndarray:
        rows = ctypes.c_int64()
        ptr = self.lib.table_multiplicities(table_id, ctypes.byref(rows))
        return np.ctypeslib.as_array(ptr, shape=(rows.value,)).copy()


OP_CONST = 0
OP_FMA = 1
OP_REDUCTION = 2
OP_SPLIT = 3
OP_U32_ADD = 4
OP_U32_SUB = 5
OP_TRIADD = 6
OP_U32_FMA = 7
OP_BYTE_TRIADD = 8
OP_POSEIDON2 = 9
OP_LOOKUP = 10
OP_LOOKUP_BUMP = 11
