"""Host CPU fingerprint for compile-cache-dir salting. ZERO heavy imports.

XLA:CPU AOT executables embed the COMPILE machine's vector features and
jax's cache key does NOT include them — loading an entry produced on a
machine with different features SIGILLs/segfaults (observed twice in
round 4: `cpu_aot_loader.cc` machine-feature mismatch warnings, then a
crash inside the cached-executable load). Salting every persistent-cache
directory with the local feature set makes a host change invalidate the
cache instead of crashing the process.

This module deliberately imports nothing beyond hashlib/platform so that
conftest.py, bench.py and scripts/ can load it by file path (see
`load_host_fingerprint` docstring) WITHOUT triggering boojum_tpu/__init__'s
jax-config side effects before they have pinned their own platform/env.
"""

import hashlib
import platform


def host_fingerprint() -> str:
    """Short stable hash of this host's CPU feature set."""
    desc = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    desc += " " + " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    return hashlib.sha256(desc.encode()).hexdigest()[:8]
