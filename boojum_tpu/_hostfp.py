"""Host CPU fingerprint for compile-cache-dir salting. ZERO heavy imports.

XLA:CPU AOT executables embed the COMPILE machine's vector features and
jax's cache key does NOT include them — loading an entry produced on a
machine with different features SIGILLs/segfaults (observed twice in
round 4: `cpu_aot_loader.cc` machine-feature mismatch warnings, then a
crash inside the cached-executable load). Salting every persistent-cache
directory with the local feature set makes a host change invalidate the
cache instead of crashing the process.

This module deliberately imports nothing beyond hashlib/platform so that
conftest.py, bench.py and scripts/ can load it WITHOUT triggering
boojum_tpu/__init__'s jax-config side effects before they have pinned
their own platform/env. Call sites use `load_host_fingerprint` via runpy:

    import runpy
    fp = runpy.run_path(
        os.path.join(root, "boojum_tpu", "_hostfp.py")
    )["load_host_fingerprint"](root)

KNOWN LIMIT (axon remote compile service): under JAX_PLATFORMS=axon the
host-side CPU AOT pieces are produced by the REMOTE compile service's
machine, whose identity the service does not expose — so this fingerprint
only guards the local-CPU dimension of the cache. If the service migrates
to a host with different CPU features, the local salt is unchanged and
stale entries could still load; there is nothing to fold in until the
service exposes a version/feature string (bench.py documents the same
caveat where it builds the axon cache dir).
"""

import hashlib
import platform


def host_fingerprint() -> str:
    """Short stable hash of this host's CPU feature set.

    Primary source is the /proc/cpuinfo feature flags. When those are
    unreadable (macOS, restricted containers), the fallback folds in
    `platform.processor()` and `platform.node()` on top of the machine
    arch — two same-arch hosts would otherwise collide on a bare
    `platform.machine()` and re-expose the cross-host AOT segfault this
    salt exists to prevent. Deliberate tradeoff: on a fallback host whose
    hostname is unstable (ephemeral containers) the salt churns and each
    run starts cold — a cold cache is a cost, a cross-host SIGILL is a
    crash, and flagless-but-stable-hostname hosts (macOS) keep reuse."""
    desc = platform.machine()
    flags_found = False
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    desc += " " + " ".join(sorted(line.split(":", 1)[1].split()))
                    flags_found = True
                    break
    except OSError:
        pass
    if not flags_found:
        desc += f" {platform.processor()} {platform.node()}"
    return hashlib.sha256(desc.encode()).hexdigest()[:8]


def load_host_fingerprint(repo_root: str) -> str:
    """Return the host fingerprint for callers that must not import the
    `boojum_tpu` package (whose __init__ configures jax on import).

    Executed via `runpy.run_path` on this file (see module docstring) the
    call is a plain function invocation; if somehow invoked from a module
    object loaded from a DIFFERENT checkout, it re-loads the _hostfp.py
    under `repo_root` by file path and delegates, so the fingerprint
    always matches the code of the repo whose cache is being salted."""
    import os

    path = os.path.join(repo_root, "boojum_tpu", "_hostfp.py")
    if os.path.abspath(path) == os.path.abspath(__file__):
        return host_fingerprint()
    import importlib.util

    spec = importlib.util.spec_from_file_location("_bt_hostfp", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.host_fingerprint()
