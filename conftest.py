"""Repo-root pytest conftest.

Forces tests onto a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware, and makes `boojum_tpu` importable. Must run
before anything imports jax.
"""

import os
import sys

# Force CPU: the ambient environment pins JAX_PLATFORMS=axon (remote TPU
# tunnel), which is for bench runs, not unit tests.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def pytest_configure(config):
    # tier-1 runs with -m 'not slow' (ROADMAP.md); register the marker so
    # slow-lane tests don't warn as unknown
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 budget (-m 'not slow')"
    )
    # gateway tests bind loopback sockets (ISSUE 11); they stay in
    # tier-1 by default, but sandboxed runners without socket permits
    # can exclude them wholesale with -m 'not gateway'
    config.addinivalue_line(
        "markers",
        "gateway: binds loopback HTTP sockets (-m 'not gateway' to skip "
        "on sandboxed runners)",
    )
    # multi-process jax.distributed tests (subprocess pairs over a
    # loopback coordinator): slow-lane by construction, selected
    # explicitly by scripts/ci_gate.sh --multihost via -m multihost
    config.addinivalue_line(
        "markers",
        "multihost: spawns jax.distributed subprocess pairs "
        "(ci_gate.sh --multihost runs these)",
    )


def pytest_collection_modifyitems(items):
    # run the AOT artifact tests LAST (stable sort): their subprocess
    # bundle build pays real XLA compiles into a fresh bundle dir every
    # run (the whole point is an isolated cache), which the repo-local
    # persistent cache cannot amortize — if the tier-1 wall-clock budget
    # dies mid-suite, that fixed cost must burn the END of the budget,
    # not starve the alphabetically-later test files
    items.sort(key=lambda it: it.fspath.basename == "test_aot.py")

# The axon sitecustomize (PYTHONPATH) registers a remote-TPU PJRT plugin whose
# backend init blocks even under JAX_PLATFORMS=cpu; deregister it outright so
# unit tests run on the local 8-device virtual CPU platform.
try:
    import jax
    from jax._src import xla_bridge

    jax.config.update("jax_platforms", "cpu")
    xla_bridge._backend_factories.pop("axon", None)
    # XLA:CPU compiles of the big unrolled prover graphs take minutes; cache
    # them persistently so only the first-ever run pays. The dir is salted
    # with the host CPU fingerprint: XLA:CPU AOT entries embed the compile
    # machine's vector features and loading them on a different host
    # segfaults (boojum_tpu/_hostfp.py has the full story). Executed by
    # file path (runpy) so boojum_tpu/__init__'s jax-config side effects
    # don't fire here.
    import runpy

    _root = os.path.dirname(os.path.abspath(__file__))
    _fp = runpy.run_path(
        os.path.join(_root, "boojum_tpu", "_hostfp.py")
    )["load_host_fingerprint"](_root)

    _cache = os.path.join(_root, f".jax_cache-{_fp}")
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:
    pass

