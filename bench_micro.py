"""Kernel microbenchmarks, one JSON line per metric.

Counterpart of the reference's criterion benches + profiling binary
(`/root/reference/benches/benchmarks.rs:20`,
`/root/reference/profiling-target/src/main.rs:17`): field mul, NTT across
sizes, Poseidon2 permutation, batch inversion — so per-round kernel work is
tracked by the record instead of ad-hoc session numbers.

All metrics chain reps ON DEVICE inside one dispatch (jax.lax.fori_loop):
behind the axon network tunnel every executable launch costs a ~10 ms round
trip, which would otherwise measure the tunnel, not the chip.

Usage: python bench_micro.py  (JSON lines on stdout; backend = ambient JAX)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from boojum_tpu.field import gl
from boojum_tpu.field import goldilocks as gf


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, gl.P, size=shape, dtype=np.uint64))


def timed_chain(body, x, reps):
    @jax.jit
    def run(v):
        return jax.lax.fori_loop(0, reps, lambda _, u: body(u), v)

    jax.block_until_ready(run(x))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(run(x))
    return (time.perf_counter() - t0) / reps


def host_identity() -> dict:
    """The machine/software identity block stamped on every JSON line
    (ISSUE 12 satellite): host CPU fingerprint, device kind, backend,
    jax/jaxlib versions — the SAME fields prover/aot.py validates bundle
    portability on, so `prove_report.py --trend` can group micro lines
    by machine and software version instead of mixing a laptop's numbers
    into a pod's series. platform_info() memoizes per process."""
    try:
        from boojum_tpu.prover.aot import platform_info

        return platform_info()
    except Exception:
        return {}


def emit(metric, value, unit, **extra):
    line = {"metric": metric, "value": value, "unit": unit, **extra}
    ident = host_identity()
    if ident:
        line["host"] = ident
    print(json.dumps(line))


def main():
    backend = jax.default_backend()

    # field mul throughput (a <- a*a + c keeps the chain live)
    n = 1 << 22
    a = _rand((n,), 1)
    c = _rand((n,), 2)
    dt = timed_chain(lambda v: gf.add(gf.mul(v, v), c), a, 8)
    emit("field_mul_elems_per_s", int(n / dt), "elems/s", backend=backend)

    # NTT fwd+inv pairs across sizes (64 columns at bench scale)
    from boojum_tpu.ntt import (
        fft_natural_to_bitreversed,
        ifft_bitreversed_to_natural,
    )

    for log_n in (12, 14, 16, 18, 20):
        cols = max(1, (1 << 22) >> log_n)
        x = _rand((cols, 1 << log_n), 3 + log_n)
        reps = 4 if log_n >= 18 else 8
        dt = timed_chain(
            lambda v: ifft_bitreversed_to_natural(
                fft_natural_to_bitreversed(v)
            ),
            x,
            reps,
        )
        emit(
            f"ntt_2^{log_n}_pair_elems_per_s",
            int(2 * cols * (1 << log_n) / dt),
            "elems/s",
            cols=cols,
            backend=backend,
        )

    # Poseidon2 permutation
    from boojum_tpu.hashes.poseidon2 import poseidon2_permutation

    st = _rand((1 << 18, 12), 40)
    dt = timed_chain(poseidon2_permutation, st, 4)
    emit(
        "poseidon2_perms_per_s", int((1 << 18) / dt), "perms/s",
        backend=backend,
    )

    # batch inversion
    b = _rand((1 << 20,), 50)
    b = jnp.where(b == 0, jnp.uint64(1), b)
    dt = timed_chain(gf.batch_inverse_xla, b, 4)
    emit(
        "batch_inverse_elems_per_s", int((1 << 20) / dt), "elems/s",
        backend=backend,
    )

    sweep_section(backend)
    resident_section(backend)
    field_section(backend)
    mesh_section(backend)


def timed_call(fn, args, reps=3):
    """Median-free simple timer for non-chainable kernels (outputs have a
    different shape than inputs, so the on-device fori_loop chain of
    timed_chain does not apply; per-call launch overhead is identical for
    both compared paths, so the ratio stays honest)."""
    jax.block_until_ready(fn(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def sweep_section(backend):
    """ISSUE 4 satellite: per-kernel u64-vs-limb microbench of the quotient
    sweep family (gate terms, cp quotient, lookup quotient, FRI fold) —
    one JSON line per kernel carrying both paths. On non-TPU backends the
    limb kernels run in Pallas interpret mode (tiny sizes, correctness
    smoke more than a perf number); on TPU they are the real fused
    kernels at bench scale."""
    from boojum_tpu.cs.gates import FmaGate
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover.fri import _fold_once_jit
    from boojum_tpu.prover.stages import (
        _build_gate_sweep,
        _cp_quotient_core,
        _lookup_quotient_core,
        chunk_columns,
    )

    on_tpu = backend == "tpu"
    n = 1 << (18 if on_tpu else 10)
    reps = 4 if on_tpu else 2
    rng = np.random.default_rng(9)

    def rnd(*s):
        return jnp.asarray(rng.integers(0, gl.P, s, dtype=np.uint64))

    def compare(name, u64_fn, limb_fn, args, elems):
        dt_u64 = timed_call(jax.jit(u64_fn), args, reps)
        dt_limb = timed_call(jax.jit(limb_fn), args, reps)
        emit(
            f"sweep_{name}_limb_elems_per_s",
            int(elems / dt_limb),
            "elems/s",
            u64_elems_per_s=int(elems / dt_u64),
            limb_over_u64=round(dt_u64 / dt_limb, 3),
            backend=backend,
            interpret=not on_tpu,
        )

    # gate terms (FMA sweep, 2 instances/row)
    geom = CSGeometry(8, 0, 6, 4)
    gates, paths = (FmaGate.instance(),), ((),)
    n_terms = FmaGate.instance().num_repetitions(geom)
    copy, const = rnd(8, n), rnd(6, n)
    a0, a1 = rnd(n_terms), rnd(n_terms)
    u64_gate = _build_gate_sweep(gates, paths, geom)
    limb_gate = ps.gate_terms_fn(gates, paths, geom)
    compare(
        "gate_terms",
        lambda c, k, x, y: u64_gate(c, None, k, x, y),
        lambda c, k, x, y: limb_gate(c, None, k, x, y),
        (copy, const, a0, a1), 8 * n,
    )

    # copy-permutation quotient
    C = 8
    chunks = tuple(tuple(c) for c in chunk_columns(C, 4))
    ks = tuple(int(x) for x in rng.integers(1, gl.P, C, dtype=np.uint64))
    z, zs = (rnd(n), rnd(n)), (rnd(n), rnd(n))
    partials = [(rnd(n), rnd(n)) for _ in range(len(chunks) - 1)]
    cp_args = (
        z, zs, partials, rnd(C, n), rnd(C, n), rnd(n), rnd(n),
        (jnp.uint64(3), jnp.uint64(5)), (jnp.uint64(7), jnp.uint64(11)),
        rnd(1 + len(chunks)), rnd(1 + len(chunks)),
    )
    compare(
        "cp_quotient",
        lambda *a: _cp_quotient_core(*a, chunks, ks),
        lambda *a: ps.cp_quotient(*a, chunks, ks),
        cp_args, C * n,
    )

    # lookup quotient (specialized, SHA-bench width)
    R, w = 4, 4
    lk_args = (
        [(rnd(n), rnd(n)) for _ in range(R)], (rnd(n), rnd(n)),
        rnd(R * w, n), rnd(n), rnd(w + 1, n), rnd(n),
        (jnp.uint64(3), jnp.uint64(5)), (jnp.uint64(7), jnp.uint64(11)),
        rnd(R + 1), rnd(R + 1),
    )
    compare(
        "lookup_quotient",
        lambda *a: _lookup_quotient_core(*a, R, w),
        lambda *a: ps.lookup_quotient(*a, R, w),
        lk_args, R * w * n,
    )

    # FRI fold
    m = 2 * n
    fold_args = ((rnd(m), rnd(m)), (jnp.uint64(3), jnp.uint64(5)), rnd(m // 2))
    compare(
        "fri_fold",
        lambda v, ch, ix: _fold_once_jit(v, ch, ix),
        lambda v, ch, ix: ps.fri_fold(v, ch, ix),
        fold_args, m,
    )


def resident_section(backend):
    """ISSUE 10 satellite: per-kernel boundary-CONVERTING vs limb-RESIDENT
    microbench — iNTT, LDE, leaf sponge, gate-terms sweep, FRI fold chain.
    The converting leg is what each kernel paid before residency (u64 in /
    u64 out: either emulated-u64 math or the limb kernel plus its
    boundary split/join); the resident leg consumes and produces (lo, hi)
    u32 planes end-to-end. Same JSON-line format as the PR 4 `sweep`
    section. On non-TPU backends the Pallas legs run in interpret mode
    (correctness smoke more than a perf number)."""
    from boojum_tpu.field import limbs
    from boojum_tpu.hashes.poseidon2 import leaf_hash, leaf_hash_planes
    from boojum_tpu.ntt import limb_ntt as LN
    from boojum_tpu.ntt import lde_from_monomial, monomial_from_values
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover import resident as RES
    from boojum_tpu.prover.fri import (
        _ch_table_np,
        _fri_fold_fn,
        _fri_fold_fn_p,
        fold_challenge_tables,
        fold_challenge_tables_p,
    )

    on_tpu = backend == "tpu"
    log_n = 18 if on_tpu else 10
    n = 1 << log_n
    reps = 4 if on_tpu else 2
    rng = np.random.default_rng(21)

    def rnd(*s):
        return jnp.asarray(rng.integers(0, gl.P, s, dtype=np.uint64))

    def compare(name, conv_fn, res_fn, conv_args, res_args, elems):
        dt_c = timed_call(conv_fn, conv_args, reps)
        dt_r = timed_call(res_fn, res_args, reps)
        emit(
            f"resident_{name}_elems_per_s",
            int(elems / dt_r),
            "elems/s",
            converting_elems_per_s=int(elems / dt_c),
            resident_over_converting=round(dt_c / dt_r, 3),
            backend=backend,
            interpret=not on_tpu,
        )

    # iNTT + LDE (the commit pipeline's transforms)
    B = 16
    x = rnd(B, n)
    xp = limbs.split(x)
    compare(
        "imono", monomial_from_values, LN.monomial_from_values_p,
        (x,), (xp,), B * n,
    )
    L = 4
    compare(
        "lde",
        lambda m: lde_from_monomial(m, L),
        lambda m: LN.lde_from_monomial_p(m, L),
        (x,), (xp,), B * n * L,
    )

    # leaf sponge over (N, width) rows
    leaves = rnd(1 << (14 if on_tpu else 11), 16)
    leaves_p = limbs.split(leaves)
    compare(
        "leaf_sponge", leaf_hash, leaf_hash_planes,
        (leaves,), (leaves_p,), int(leaves.shape[0]) * 16,
    )

    # gate-terms sweep (the fused limb kernel: boundary split/join vs
    # plane-resident in/out; same in-kernel core)
    from boojum_tpu.cs.gates import FmaGate
    from boojum_tpu.cs.types import CSGeometry

    geom = CSGeometry(8, 0, 6, 4)
    gates, paths = (FmaGate.instance(),), ((),)
    n_terms = FmaGate.instance().num_repetitions(geom)
    copy, const = rnd(8, n), rnd(6, n)
    a0 = [int(v) for v in np.asarray(rnd(n_terms))]
    a1 = [int(v) for v in np.asarray(rnd(n_terms))]
    gate = ps.gate_terms_fn(gates, paths, geom)
    table = jnp.asarray(RES.sc_table_np(a0, a1))
    compare(
        "gate_terms",
        lambda c, k: gate(c, None, k, jnp.asarray(np.array(a0, np.uint64)),
                          jnp.asarray(np.array(a1, np.uint64))),
        lambda c, k: gate.planes(c, None, k, table),
        (copy, const), (limbs.split(copy), limbs.split(const)), 8 * n,
    )

    # FRI fold chain (k=3): the converting chain pays a split+join per
    # fold; the resident chain stays planes across all three
    m = 2 * n
    log_m = m.bit_length() - 1
    c0, c1 = rnd(m), rnd(m)
    ch = (3, 5)
    tabs_u = tuple(fold_challenge_tables(log_m, 3))
    tabs_p = tuple(fold_challenge_tables_p(log_m, 3))
    ch01 = jnp.asarray(np.array(ch, dtype=np.uint64))
    tb = jnp.asarray(_ch_table_np(ch))
    c0p, c1p = limbs.split(c0), limbs.split(c1)
    compare(
        "fri_fold_k3",
        lambda a, b: _fri_fold_fn(3, True, None)(a, b, ch01, tabs_u),
        lambda a, b: _fri_fold_fn_p(3, None)(a, b, tb, tabs_p),
        (c0, c1), (c0p, c1p), m,
    )


def field_section(backend):
    """ISSUE 19 satellite: per-kernel Goldilocks-limb vs BabyBear
    plane-free microbench — iNTT, LDE, leaf sponge, gate-terms sweep,
    FRI fold chain. The Goldilocks leg is the limb-RESIDENT twin (the
    best Goldilocks path: (lo, hi) u32 planes, 8 bytes/elem); the
    BabyBear leg is the plane-free `_bb` kernel (ONE u32 lane,
    4 bytes/elem). Each line carries both backends' throughput plus the
    bytes-per-element of each, so `prove_report.py --trend` tracks the
    two field backends as separate series and the HBM-halving claim
    stays a measured number, not an assertion."""
    from boojum_tpu.field import babybear as bb
    from boojum_tpu.field import limbs
    from boojum_tpu.field.spec import BABYBEAR
    from boojum_tpu.hashes.poseidon2 import leaf_hash_planes
    from boojum_tpu.ntt import bb_ntt
    from boojum_tpu.ntt import limb_ntt as LN
    from boojum_tpu.prover import bb_kernels as K
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover import resident as RES
    from boojum_tpu.prover.fri import (
        _ch_table_np,
        _fri_fold_fn_p,
        fold_challenge_tables_p,
    )

    on_tpu = backend == "tpu"
    log_n = 18 if on_tpu else 10
    Lf = 4 if on_tpu else 2
    n = 1 << log_n
    N = n * Lf
    reps = 4 if on_tpu else 2
    rng = np.random.default_rng(33)

    def rnd_gl(*s):
        return jnp.asarray(rng.integers(0, gl.P, s, dtype=np.uint64))

    def rnd_bb(*s):
        return jnp.asarray(rng.integers(0, bb.P, s, dtype=np.uint32))

    def compare(name, gl_fn, gl_args, bb_fn, bb_args, gl_elems, bb_elems):
        dt_gl = timed_call(gl_fn, gl_args, reps)
        dt_bb = timed_call(bb_fn, bb_args, reps)
        gl_tp, bb_tp = gl_elems / dt_gl, bb_elems / dt_bb
        emit(
            f"field_{name}_bb_elems_per_s",
            int(bb_tp),
            "elems/s",
            gl_limb_elems_per_s=int(gl_tp),
            bb_over_gl=round(bb_tp / gl_tp, 3),
            bytes_per_elem_bb=4,
            bytes_per_elem_gl=8,
            backend=backend,
            interpret=not on_tpu,
        )

    # iNTT (values -> monomial) + LDE: limb planes vs one u32 lane
    B = 16
    xp = limbs.split(rnd_gl(B, n))
    xb = rnd_bb(B, n)
    compare(
        "imono",
        LN.monomial_from_values_p, (xp,),
        lambda v: bb_ntt.monomial_from_values_bb(v, log_n), (xb,),
        B * n, B * n,
    )
    shift = BABYBEAR.multiplicative_generator
    compare(
        "lde",
        lambda m: LN.lde_from_monomial_p(m, Lf), (xp,),
        lambda m: bb_ntt.lde_from_monomial_bb(m, log_n, Lf, shift), (xb,),
        B * n * Lf, B * n * Lf,
    )

    # leaf sponge: width-12 Goldilocks permutation over (lo, hi) planes
    # vs width-16 BabyBear permutation over bare lanes
    T = 1 << (14 if on_tpu else 11)
    leaves_p = limbs.split(rnd_gl(T, 16))
    cols_b = rnd_bb(16, T)
    compare(
        "leaf_sponge",
        leaf_hash_planes, (leaves_p,),
        K.leaf_digests_bb, (cols_b,),
        T * 16, T * 16,
    )

    # fused quotient sweep: the plane-resident gate-terms kernel vs the
    # BabyBear coset sweep (random division tables — kernel throughput
    # does not depend on table values)
    from boojum_tpu.cs.gates import FmaGate
    from boojum_tpu.cs.types import CSGeometry

    geom = CSGeometry(8, 0, 6, 4)
    gate = ps.gate_terms_fn((FmaGate.instance(),), ((),), geom)
    n_terms = FmaGate.instance().num_repetitions(geom)
    copy_p = limbs.split(rnd_gl(8, n))
    const_p = limbs.split(rnd_gl(6, n))
    a0 = [int(v) for v in np.asarray(rnd_gl(n_terms))]
    a1 = [int(v) for v in np.asarray(rnd_gl(n_terms))]
    table = jnp.asarray(RES.sc_table_np(a0, a1))
    compare(
        "gate_terms",
        lambda c, k: gate.planes(c, None, k, table), (copy_p, const_p),
        lambda w, al, cp, lt, zh, bi: K.coset_sweep_terms_bb(
            w, al, cp, lt, zh, bi, Lf
        ),
        (rnd_bb(N), rnd_bb(4), rnd_bb(2), rnd_bb(N), rnd_bb(N), rnd_bb(N)),
        8 * n, N,
    )

    # FRI fold chain: one k=3 plane-resident fold (GF(p^2): 2 u64/elem)
    # vs the three chained factor-2 `_bb` folds a BabyBear prove
    # actually dispatches (GF(p^4): 4 u32/elem)
    m = N
    log_m = m.bit_length() - 1
    c0p, c1p = limbs.split(rnd_gl(m)), limbs.split(rnd_gl(m))
    tb = jnp.asarray(_ch_table_np((3, 5)))
    tabs_p = tuple(fold_challenge_tables_p(log_m, 3))
    gl_fold = _fri_fold_fn_p(3, None)

    cw = rnd_bb(4, m)
    betas = [rnd_bb(4) for _ in range(3)]
    invtabs = [rnd_bb(m >> (r + 1)) for r in range(3)]

    def bb_fold_chain(c, b0, b1, b2, t0, t1, t2):
        c = K.fri_fold_bb(c, b0, t0)
        c = K.fri_fold_bb(c, b1, t1)
        return K.fri_fold_bb(c, b2, t2)

    compare(
        "fri_fold_chain",
        lambda a, b: gl_fold(a, b, tb, tabs_p), (c0p, c1p),
        bb_fold_chain, (cw, *betas, *invtabs),
        2 * m, 4 * m,
    )


def mesh_section(backend):
    """ISSUE 5 satellite: per-kernel GSPMD-vs-shard_map microbench on the
    largest ('col','row') mesh the local devices allow — the coset
    evaluation (scale+NTT+pivot), the leaf sponge over pivoted rows, the
    FRI fold chain, and the bare all_to_all layout pivot. GSPMD timings
    dispatch the MESHLESS jitted graph on column/row-sharded operands
    (XLA inserts the collectives); shard_map timings run the explicit
    per-chip graphs from parallel/shard_sweep.py. Skipped (no JSON lines)
    on single-device processes."""
    import boojum_tpu.parallel.shard_sweep as SS
    from boojum_tpu.parallel.sharding import prover_mesh
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    D = 1 << (len(devs).bit_length() - 1)  # largest power of two
    if D < 2:
        return
    ncol = 1 << ((D.bit_length() - 1) // 2)
    mesh = Mesh(
        np.array(devs[:D]).reshape(ncol, D // ncol),
        axis_names=("col", "row"),
    )
    on_tpu = backend == "tpu"
    log_n, L, B = (18, 8, 32) if on_tpu else (10, 2, 16)
    n = 1 << log_n
    N = n * L
    rng = np.random.default_rng(11)

    def rnd(*s):
        return jnp.asarray(rng.integers(0, gl.P, s, dtype=np.uint64))

    def emit_pair(name, dt_gspmd, dt_sm, elems):
        emit(
            f"mesh_{name}_sm_elems_per_s",
            int(elems / dt_sm),
            "elems/s",
            gspmd_elems_per_s=int(elems / dt_gspmd),
            sm_over_gspmd=round(dt_gspmd / dt_sm, 3),
            mesh_shape=[int(mesh.shape["col"]), int(mesh.shape["row"])],
            backend=backend,
        )

    col_sh = NamedSharding(mesh, P(("col", "row")))

    # coset evaluation: per-chip scale+NTT then the explicit pivot vs the
    # meshless graph GSPMD-partitioned from a column-sharded operand
    from boojum_tpu.prover.prover import _coset_eval_q

    mono = rnd(B, n)
    scale_q = rnd(L, n)
    ci = jnp.int32(0)
    mono_g = jax.device_put(mono, col_sh)
    # GSPMD legs trace under the ACTIVE mesh, exactly like a real gspmd
    # prove — pallas_enabled()'s active-mesh veto then keeps the plain XLA
    # bodies GSPMD can partition (a meshless trace on TPU would hand a
    # pallas_call over sharded operands to the SPMD partitioner: not the
    # graph the mesh path ever dispatches, and not partitionable)
    with prover_mesh(mesh):
        dt_g = timed_call(
            lambda m_, s_, c_: _coset_eval_q(m_, s_, c_),
            (mono_g, scale_q, ci),
        )
    mono_p = SS.pad_cols_sharded(mono, mesh)
    dt_s = timed_call(
        SS._coset_eval_fn(mesh, B), (mono_p, scale_q, ci)
    )
    emit_pair("coset_eval", dt_g, dt_s, B * n)

    # the materialized commit tail (LDE + col->row pivot + leaf sponge),
    # SAME work both sides: the meshless graph GSPMD-partitioned from the
    # column-sharded monomials (XLA inserts the pivot as a resharding of
    # the transpose) vs the fused per-chip shard_map graph
    from boojum_tpu.hashes.poseidon2 import leaf_hash_xla
    from boojum_tpu.ntt import lde_from_monomial

    def _lde_leaf(m):
        lde = lde_from_monomial(m, L)
        return lde, leaf_hash_xla(lde.reshape(m.shape[0], -1).T)

    with prover_mesh(mesh):
        dt_g = timed_call(jax.jit(_lde_leaf), (mono_g,))
    use_limb = SS.leaf_limb_ok(B, N // SS.mesh_devices(mesh))
    lde_fn = SS._lde_pivot_leaf_fn(mesh, L, B, use_limb)
    dt_s = timed_call(lde_fn, (mono_p,))
    emit_pair("leaf_sponge", dt_g, dt_s, N * B)

    # FRI fold chain (k=3)
    from boojum_tpu.prover.fri import _fri_fold_fn

    m = N
    c0, c1 = rnd(m), rnd(m)
    ch01 = rnd(2)
    tabs = tuple(rnd(m >> (j + 1)) for j in range(3))
    c0g = jax.device_put(c0, col_sh)
    c1g = jax.device_put(c1, col_sh)
    with prover_mesh(mesh):
        dt_g = timed_call(
            _fri_fold_fn(3, False, None), (c0g, c1g, ch01, tabs)
        )
    if SS.fold_shards_ok(m, 3, mesh):
        # both sides fold the same pre-sharded c0g/c1g; only the fold
        # tables still need their device_put (the sm chain consumes them
        # sharded, the meshless graph above took them from host)
        tabs_s = tuple(jax.device_put(t, col_sh) for t in tabs)
        dt_s = timed_call(
            _fri_fold_fn(3, False, mesh), (c0g, c1g, ch01, tabs_s)
        )
        emit_pair("fri_fold_k3", dt_g, dt_s, m)

    # the bare col->row layout pivot: explicit all_to_all vs the implicit
    # resharding GSPMD inserts for the same layout change
    from jax.experimental.shard_map import shard_map

    flat = rnd(B, N)
    col2_sh = NamedSharding(mesh, P(("col", "row"), None))
    flat_g = jax.device_put(flat, col2_sh)
    dt_g = timed_call(
        jax.jit(
            lambda x: x,
            out_shardings=NamedSharding(mesh, P(None, ("col", "row"))),
        ),
        (flat_g,),
    )
    piv = jax.jit(
        shard_map(
            lambda x: jax.lax.all_to_all(
                x, ("col", "row"), split_axis=1, concat_axis=0, tiled=True
            ),
            mesh=mesh,
            in_specs=(P(("col", "row"), None),),
            out_specs=P(None, ("col", "row")),
            check_rep=False,
        )
    )
    dt_s = timed_call(piv, (flat_g,))
    emit_pair("pivot_all_to_all", dt_g, dt_s, B * N)


if __name__ == "__main__":
    main()
