"""Kernel microbenchmarks, one JSON line per metric.

Counterpart of the reference's criterion benches + profiling binary
(`/root/reference/benches/benchmarks.rs:20`,
`/root/reference/profiling-target/src/main.rs:17`): field mul, NTT across
sizes, Poseidon2 permutation, batch inversion — so per-round kernel work is
tracked by the record instead of ad-hoc session numbers.

All metrics chain reps ON DEVICE inside one dispatch (jax.lax.fori_loop):
behind the axon network tunnel every executable launch costs a ~10 ms round
trip, which would otherwise measure the tunnel, not the chip.

Usage: python bench_micro.py  (JSON lines on stdout; backend = ambient JAX)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from boojum_tpu.field import gl
from boojum_tpu.field import goldilocks as gf


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, gl.P, size=shape, dtype=np.uint64))


def timed_chain(body, x, reps):
    @jax.jit
    def run(v):
        return jax.lax.fori_loop(0, reps, lambda _, u: body(u), v)

    jax.block_until_ready(run(x))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(run(x))
    return (time.perf_counter() - t0) / reps


def emit(metric, value, unit, **extra):
    print(json.dumps({"metric": metric, "value": value, "unit": unit, **extra}))


def main():
    backend = jax.default_backend()

    # field mul throughput (a <- a*a + c keeps the chain live)
    n = 1 << 22
    a = _rand((n,), 1)
    c = _rand((n,), 2)
    dt = timed_chain(lambda v: gf.add(gf.mul(v, v), c), a, 8)
    emit("field_mul_elems_per_s", int(n / dt), "elems/s", backend=backend)

    # NTT fwd+inv pairs across sizes (64 columns at bench scale)
    from boojum_tpu.ntt import (
        fft_natural_to_bitreversed,
        ifft_bitreversed_to_natural,
    )

    for log_n in (12, 14, 16, 18, 20):
        cols = max(1, (1 << 22) >> log_n)
        x = _rand((cols, 1 << log_n), 3 + log_n)
        reps = 4 if log_n >= 18 else 8
        dt = timed_chain(
            lambda v: ifft_bitreversed_to_natural(
                fft_natural_to_bitreversed(v)
            ),
            x,
            reps,
        )
        emit(
            f"ntt_2^{log_n}_pair_elems_per_s",
            int(2 * cols * (1 << log_n) / dt),
            "elems/s",
            cols=cols,
            backend=backend,
        )

    # Poseidon2 permutation
    from boojum_tpu.hashes.poseidon2 import poseidon2_permutation

    st = _rand((1 << 18, 12), 40)
    dt = timed_chain(poseidon2_permutation, st, 4)
    emit(
        "poseidon2_perms_per_s", int((1 << 18) / dt), "perms/s",
        backend=backend,
    )

    # batch inversion
    b = _rand((1 << 20,), 50)
    b = jnp.where(b == 0, jnp.uint64(1), b)
    dt = timed_chain(gf.batch_inverse_xla, b, 4)
    emit(
        "batch_inverse_elems_per_s", int((1 << 20) / dt), "elems/s",
        backend=backend,
    )


if __name__ == "__main__":
    main()
