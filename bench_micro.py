"""Kernel microbenchmarks, one JSON line per metric.

Counterpart of the reference's criterion benches + profiling binary
(`/root/reference/benches/benchmarks.rs:20`,
`/root/reference/profiling-target/src/main.rs:17`): field mul, NTT across
sizes, Poseidon2 permutation, batch inversion — so per-round kernel work is
tracked by the record instead of ad-hoc session numbers.

All metrics chain reps ON DEVICE inside one dispatch (jax.lax.fori_loop):
behind the axon network tunnel every executable launch costs a ~10 ms round
trip, which would otherwise measure the tunnel, not the chip.

Usage: python bench_micro.py  (JSON lines on stdout; backend = ambient JAX)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from boojum_tpu.field import gl
from boojum_tpu.field import goldilocks as gf


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, gl.P, size=shape, dtype=np.uint64))


def timed_chain(body, x, reps):
    @jax.jit
    def run(v):
        return jax.lax.fori_loop(0, reps, lambda _, u: body(u), v)

    jax.block_until_ready(run(x))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(run(x))
    return (time.perf_counter() - t0) / reps


def emit(metric, value, unit, **extra):
    print(json.dumps({"metric": metric, "value": value, "unit": unit, **extra}))


def main():
    backend = jax.default_backend()

    # field mul throughput (a <- a*a + c keeps the chain live)
    n = 1 << 22
    a = _rand((n,), 1)
    c = _rand((n,), 2)
    dt = timed_chain(lambda v: gf.add(gf.mul(v, v), c), a, 8)
    emit("field_mul_elems_per_s", int(n / dt), "elems/s", backend=backend)

    # NTT fwd+inv pairs across sizes (64 columns at bench scale)
    from boojum_tpu.ntt import (
        fft_natural_to_bitreversed,
        ifft_bitreversed_to_natural,
    )

    for log_n in (12, 14, 16, 18, 20):
        cols = max(1, (1 << 22) >> log_n)
        x = _rand((cols, 1 << log_n), 3 + log_n)
        reps = 4 if log_n >= 18 else 8
        dt = timed_chain(
            lambda v: ifft_bitreversed_to_natural(
                fft_natural_to_bitreversed(v)
            ),
            x,
            reps,
        )
        emit(
            f"ntt_2^{log_n}_pair_elems_per_s",
            int(2 * cols * (1 << log_n) / dt),
            "elems/s",
            cols=cols,
            backend=backend,
        )

    # Poseidon2 permutation
    from boojum_tpu.hashes.poseidon2 import poseidon2_permutation

    st = _rand((1 << 18, 12), 40)
    dt = timed_chain(poseidon2_permutation, st, 4)
    emit(
        "poseidon2_perms_per_s", int((1 << 18) / dt), "perms/s",
        backend=backend,
    )

    # batch inversion
    b = _rand((1 << 20,), 50)
    b = jnp.where(b == 0, jnp.uint64(1), b)
    dt = timed_chain(gf.batch_inverse_xla, b, 4)
    emit(
        "batch_inverse_elems_per_s", int((1 << 20) / dt), "elems/s",
        backend=backend,
    )

    sweep_section(backend)


def timed_call(fn, args, reps=3):
    """Median-free simple timer for non-chainable kernels (outputs have a
    different shape than inputs, so the on-device fori_loop chain of
    timed_chain does not apply; per-call launch overhead is identical for
    both compared paths, so the ratio stays honest)."""
    jax.block_until_ready(fn(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def sweep_section(backend):
    """ISSUE 4 satellite: per-kernel u64-vs-limb microbench of the quotient
    sweep family (gate terms, cp quotient, lookup quotient, FRI fold) —
    one JSON line per kernel carrying both paths. On non-TPU backends the
    limb kernels run in Pallas interpret mode (tiny sizes, correctness
    smoke more than a perf number); on TPU they are the real fused
    kernels at bench scale."""
    from boojum_tpu.cs.gates import FmaGate
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover.fri import _fold_once_jit
    from boojum_tpu.prover.stages import (
        _build_gate_sweep,
        _cp_quotient_core,
        _lookup_quotient_core,
        chunk_columns,
    )

    on_tpu = backend == "tpu"
    n = 1 << (18 if on_tpu else 10)
    reps = 4 if on_tpu else 2
    rng = np.random.default_rng(9)

    def rnd(*s):
        return jnp.asarray(rng.integers(0, gl.P, s, dtype=np.uint64))

    def compare(name, u64_fn, limb_fn, args, elems):
        dt_u64 = timed_call(jax.jit(u64_fn), args, reps)
        dt_limb = timed_call(jax.jit(limb_fn), args, reps)
        emit(
            f"sweep_{name}_limb_elems_per_s",
            int(elems / dt_limb),
            "elems/s",
            u64_elems_per_s=int(elems / dt_u64),
            limb_over_u64=round(dt_u64 / dt_limb, 3),
            backend=backend,
            interpret=not on_tpu,
        )

    # gate terms (FMA sweep, 2 instances/row)
    geom = CSGeometry(8, 0, 6, 4)
    gates, paths = (FmaGate.instance(),), ((),)
    n_terms = FmaGate.instance().num_repetitions(geom)
    copy, const = rnd(8, n), rnd(6, n)
    a0, a1 = rnd(n_terms), rnd(n_terms)
    u64_gate = _build_gate_sweep(gates, paths, geom)
    limb_gate = ps.gate_terms_fn(gates, paths, geom)
    compare(
        "gate_terms",
        lambda c, k, x, y: u64_gate(c, None, k, x, y),
        lambda c, k, x, y: limb_gate(c, None, k, x, y),
        (copy, const, a0, a1), 8 * n,
    )

    # copy-permutation quotient
    C = 8
    chunks = tuple(tuple(c) for c in chunk_columns(C, 4))
    ks = tuple(int(x) for x in rng.integers(1, gl.P, C, dtype=np.uint64))
    z, zs = (rnd(n), rnd(n)), (rnd(n), rnd(n))
    partials = [(rnd(n), rnd(n)) for _ in range(len(chunks) - 1)]
    cp_args = (
        z, zs, partials, rnd(C, n), rnd(C, n), rnd(n), rnd(n),
        (jnp.uint64(3), jnp.uint64(5)), (jnp.uint64(7), jnp.uint64(11)),
        rnd(1 + len(chunks)), rnd(1 + len(chunks)),
    )
    compare(
        "cp_quotient",
        lambda *a: _cp_quotient_core(*a, chunks, ks),
        lambda *a: ps.cp_quotient(*a, chunks, ks),
        cp_args, C * n,
    )

    # lookup quotient (specialized, SHA-bench width)
    R, w = 4, 4
    lk_args = (
        [(rnd(n), rnd(n)) for _ in range(R)], (rnd(n), rnd(n)),
        rnd(R * w, n), rnd(n), rnd(w + 1, n), rnd(n),
        (jnp.uint64(3), jnp.uint64(5)), (jnp.uint64(7), jnp.uint64(11)),
        rnd(R + 1), rnd(R + 1),
    )
    compare(
        "lookup_quotient",
        lambda *a: _lookup_quotient_core(*a, R, w),
        lambda *a: ps.lookup_quotient(*a, R, w),
        lk_args, R * w * n,
    )

    # FRI fold
    m = 2 * n
    fold_args = ((rnd(m), rnd(m)), (jnp.uint64(3), jnp.uint64(5)), rnd(m // 2))
    compare(
        "fri_fold",
        lambda v, ch, ix: _fold_once_jit(v, ch, ix),
        lambda v, ch, ix: ps.fri_fold(v, ch, ix),
        fold_args, m,
    )


if __name__ == "__main__":
    main()
