"""Headline benchmark. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Current headline: full e2e proof wall-clock on the toy arithmetic circuit
(until the SHA-256 gadget circuit lands, after which this switches to the
reference bench geometry: 2^16 rows, 60 copy cols, lookups — BASELINE.md).
vs_baseline is wall-clock speedup vs the most recent recorded run in
BENCH_BASELINE.json if present, else 1.0.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax

    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify

    geom = CSGeometry(
        num_columns_under_copy_permutation=16,
        num_witness_columns=0,
        num_constant_columns=6,
        max_allowed_constraint_degree=4,
    )
    config = ProofConfig(
        fri_lde_factor=8,
        merkle_tree_cap_size=16,
        num_queries=50,
        pow_bits=0,
        fri_final_degree=16,
    )
    log_n = int(os.environ.get("BENCH_LOG_N", "10"))
    cs = ConstraintSystem(geom, 1 << log_n)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    # fill ~full trace with FMA chains
    per_row = FmaGate.instance().num_repetitions(geom)
    steps = ((1 << log_n) - 8) * per_row
    for _ in range(steps):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    setup = generate_setup(asm, config)

    # warm-up (compile) then timed runs
    proof = prove(asm, setup, config)
    assert verify(setup.vk, proof, asm.gates)
    t0 = time.perf_counter()
    reps = 1
    for _ in range(reps):
        proof = prove(asm, setup, config)
    wall = (time.perf_counter() - t0) / reps

    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(base_path):
        try:
            base = json.load(open(base_path))
            if base.get("metric") == f"fma_2^{log_n}_prove_wall" and base.get("value"):
                vs = base["value"] / wall
        except Exception:
            pass
    print(json.dumps({
        "metric": f"fma_2^{log_n}_prove_wall",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
