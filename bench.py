"""Headline benchmark. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline circuit: the reference's SHA-256 bench (8 kB message through the
lookup-table SHA-256 gadget; reference src/gadgets/sha256/mod.rs:269 and
README "For curions in benchmarks": 60 copy columns, 8 width-4 lookup
sub-arguments, LDE factor 8, cap 16; the reference trace is 2^16 rows — the
2^17 passed to the CS below is a CAPACITY bound, pad_and_shrink rounds the
actual trace to the smallest power of two that fits and the bench prints the
realized trace length on stderr). The timed quantity is the proving
wall-clock with warm compile caches (the reference's "Proving is done,
taken ..." line measures the same region).

Robustness: the remote compile service behind the axon tunnel takes minutes
per big fused graph on a cold cache (and occasionally drops a compile RPC).
A watchdog thread guarantees the JSON line is printed within BENCH_BUDGET_S
seconds no matter what: if the full protocol hasn't finished by then, the
line carries whatever was measured so far plus a "status" field, and the
process exits 0. A completed run reports status "ok".

Cold-start posture (ISSUE 1): before the first prove the bench runs the
parallel PRECOMPILE sweep (boojum_tpu/prover/precompile.py) — the split
prover kernel library compiles concurrently through a thread pool instead
of serially at first dispatch, and lands in the persistent cache below. A
compile LEDGER (per-kernel trace/compile seconds, cache hit/miss counts)
rides along on every JSON line and is written to BENCH_LEDGER_JSON, so a
timeout is diagnosable from the JSON alone and compile-bill regressions
are visible across rounds.

AOT artifacts (ISSUE 8): with BOOJUM_TPU_AOT_DIR set the bench consults
the artifact store (boojum_tpu/prover/aot.py) before anything traces —
a matching pre-built bundle replaces the precompile sweep outright, the
warm phase becomes O(deserialization), and the ledger attributes it via
aot_hits/aot_deserialize_s instead of compile seconds. Build the bundle
once per (circuit, config, platform) with `--build-artifacts` (or
scripts/build_artifacts.py) and every later cold process skips the
compile bill entirely.

Usage: python bench.py [--precompile-only] [--no-precompile] [--service]
                       [--build-artifacts]
  --precompile-only runs synthesis + the parallel precompile, emits the
  ledger JSON line and exits — a cache-warming step to run before a bench
  or a multihost round.
  --build-artifacts runs synthesis + the full AOT bundle build (kernel
  library + setup + one capture prove, persistent cache redirected into
  the bundle) under BOOJUM_TPU_AOT_DIR (default ./aot_artifacts), emits
  the ledger line and exits.
  --no-precompile skips the pre-prove parallel precompile sweep (the
  sweep runs BY DEFAULT before the warm-up prove: round 4's watchdog
  burned the whole budget on serial cold compiles, so BENCH lines never
  measured a prove; equivalent to BENCH_PRECOMPILE=0).
  --service measures THROUGHPUT instead of single-proof wall: after the
  warm-up prove, BENCH_SERVICE_REQS requests (default 4) of the bench
  circuit drain through the boojum_tpu/service/ scheduler
  (shape-bucketed queue, device-resident caches, shard- vs
  proof-parallel placement) and the JSON line's metric becomes
  <circuit>_service_proofs_per_sec with the service summary (placements,
  queue, cache hits/evictions) attached — so BENCH rounds can track
  proofs/sec, not just prove wall. BOOJUM_TPU_SERVICE_* flags apply.

Environment knobs:
  BENCH_CIRCUIT = sha256 (default) | fma
  BENCH_SHA_BYTES = message size (default 8192)
  BENCH_LOG_N = fma-mode trace log2 size (default 10)
  BENCH_REPS = timed repetitions (default 3)
  BENCH_BUDGET_S = hard wall-clock budget before the watchdog reports
      (default 1500)
  BENCH_LDE = FRI commit rate override (default 8 sha / 4 fma; the
      quotient still evaluates at the degree-derived rate — BENCH_LDE=2 is
      the Era main-VM golden-proof commit rate and what 2^20-row traces
      use to stay inside HBM)
  BENCH_QUERIES = FRI query count (default 50; the reference's LDE-2
      golden proof uses 100)
  BENCH_SKIP_NTT = 1 skips the NTT-throughput side metric
  BENCH_PRECOMPILE = 0 skips the pre-prove parallel precompile sweep
      (same as --no-precompile; the sweep is ON by default)
  BENCH_PRECOMPILE_WORKERS = thread-pool width for it (default 8)
  BENCH_CACHE_MAX_BYTES = size cap for each repo-local .jax_cache_bench_*
      dir; oldest entries are evicted above it (default 8 GiB, 0 disables
      — min_compile_time_secs=0.0 below persists EVERY graph, so the
      caches would otherwise grow without bound across shapes and rounds)
  BENCH_LEDGER_JSON = compile-ledger artifact path (default
      compile_ledger.json next to this file)
  BENCH_LOG_COMPILES = 0 disables jax_log_compiles (on by default so the
      ledger can attribute dispatch-time compiles to graph names)
  BOOJUM_TPU_BLACKBOX / BOOJUM_TPU_STALL_S arm the black-box recorder
      (boojum_tpu/utils/blackbox.py): crash-safe heartbeat sidecar +
      stall/SIGTERM stack dumps into the report artifact (ISSUE 15)
  BENCH_SETUP_DEADLINE_S / BENCH_WARMUP_DEADLINE_S / BENCH_REP_DEADLINE_S
      per-phase blackbox deadline alarms (defaults 300/600/60; =0
      disables one; no-ops when the blackbox is not armed)
  BOOJUM_TPU_REPORT = <path.jsonl> records every prove (warm-up + reps)
      through the flight recorder and appends one labeled ProveReport
      JSONL line each: hierarchical span tree, metrics (device memory,
      transfer bytes, NTT/Merkle/FRI counts), Fiat–Shamir digest
      checkpoints, compile-ledger summary. Inspect/diff with
      scripts/prove_report.py (see BASELINE.md "Observability protocol").

JSON line schema 2: adds "schema", promotes the per-stage split to every
line (warm-up split until the first timed rep lands, so even a watchdog
line carries one) and "peak_mem" (device high-water where the backend
exposes memory_stats, live-buffer census bytes, host max RSS). Non-"ok"
lines additionally carry "span_tree": the partial flight-recorder span
tree of the prove in flight (open spans annotated "unclosed"), so a
watchdog timeout localizes to the exact sub-stage instead of `{}`.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_T0 = time.perf_counter()


def _log(msg):
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _prune_bench_caches(root, exclude=None):
    """Size-capped prune of every repo-local .jax_cache_bench_* dir.

    jax_persistent_cache_min_compile_time_secs=0.0 below persists EVERY
    graph (~500 per 2^16 prove) with no eviction of its own, so across
    shapes and rounds the bench caches grow without bound (ADVICE.md
    round 4). Above BENCH_CACHE_MAX_BYTES per dir (default 8 GiB, 0
    disables) the oldest entries are deleted until under budget.

    Two classes of entry are NEVER evicted, whatever their age:

    - anything touched since THIS process started (mtime or atime — the
      LRU cache's `-atime` sibling files — at/after _T0): the current
      run's shape bucket, which the precompile/AOT warm phase has just
      read or written. The prune therefore runs AFTER that phase (main()
      calls it), not at import time — an import-time prune used to be
      able to evict the very entries the run was about to need, turning
      a warm round cold;
    - entries installed from a loaded AOT artifact bundle
      (prover/aot.py tracks the basenames): evicting those silently
      re-opens the compile bill the bundle exists to close.

    Entries are pruned as whole `<key>-cache`/`<key>-atime` STEMS
    (oldest stem first, by its newest file) — the old per-file pass
    could delete a `-cache` file and orphan its `-atime` sibling."""
    try:
        budget = float(
            os.environ.get("BENCH_CACHE_MAX_BYTES", str(8 << 30))
        )
    except ValueError:
        budget = float(8 << 30)
    if budget <= 0:
        return
    protected_names = set()
    try:
        from boojum_tpu.prover import aot as _aot

        protected_names = _aot.loaded_cache_files()
    except Exception:
        pass
    t0_epoch = time.time() - (time.perf_counter() - _T0)
    for d in sorted(os.listdir(root)):
        cache_dir = os.path.join(root, d)
        if not d.startswith(".jax_cache_bench_") or not os.path.isdir(cache_dir):
            continue
        if d == exclude:
            continue
        stems: dict = {}  # stem -> [newest_ts, size, paths, protected]
        total = 0
        for base, _dirs, files in os.walk(cache_dir):
            for fname in files:
                p = os.path.join(base, fname)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                stem = fname
                for suffix in ("-cache", "-atime"):
                    if stem.endswith(suffix):
                        stem = stem[: -len(suffix)]
                        break
                ts = max(st.st_mtime, st.st_atime)
                ent = stems.setdefault(stem, [0.0, 0, [], False])
                ent[0] = max(ent[0], ts)
                ent[1] += st.st_size
                ent[2].append((p, st.st_size))
                if fname in protected_names or ts >= t0_epoch:
                    ent[3] = True
                total += st.st_size
        if total <= budget:
            continue
        order = sorted(stems.values())  # oldest stem first
        freed = 0
        kept_protected = 0
        for ts, size, paths, protected in order:
            if total - freed <= budget:
                break
            if protected:
                kept_protected += 1
                continue
            for p, sz in paths:
                try:
                    os.remove(p)
                except OSError:
                    # only count bytes ACTUALLY freed — a failed remove
                    # (permissions, concurrent prune) must not satisfy
                    # the budget on paper while the dir stays over cap
                    continue
                freed += sz
        _log(
            f"pruned {freed / 2**20:.0f} MiB from {d} "
            f"({total / 2**20:.0f} MiB > cap {budget / 2**20:.0f} MiB"
            + (
                f"; kept {kept_protected} protected stems"
                if kept_protected
                else ""
            )
            + ")"
        )


def _enable_compile_cache():
    """Persist compiled executables across bench runs — the remote compile
    service behind the tunnel takes minutes per big fused graph, which
    otherwise dominates every run's wall-clock before the first timed rep."""
    try:
        import jax

        # NOT the tests' .jax_cache, and salted by the platform string AND
        # the local host's CPU fingerprint: the axon remote compile service
        # runs on a different host, and its CPU-flavored AOT entries SIGILL
        # the local machine when a local CPU process loads them — caches
        # from different platforms or hosts must never mix (same rule as
        # boojum_tpu/__init__.py's default cache; two segfaults in round 4
        # traced to cross-host CPU AOT entries). _hostfp is executed by
        # file path (runpy) so boojum_tpu/__init__'s side effects don't
        # fire yet. Caveat: for JAX_PLATFORMS=axon the fingerprint only
        # guards the LOCAL-CPU dimension — the remote compile service
        # exposes no host identity to fold into the salt (see the
        # _hostfp.py module docstring).
        import runpy

        _root = os.path.dirname(os.path.abspath(__file__))
        _fp = runpy.run_path(
            os.path.join(_root, "boojum_tpu", "_hostfp.py")
        )["load_host_fingerprint"](_root)

        plat = (
            os.environ.get("JAX_PLATFORMS", "").strip().replace(",", "-")
            or "default"
        )
        cache = os.path.join(_root, f".jax_cache_bench_{plat}_{_fp}")
        # at import time, prune every OTHER platform/host's bench cache
        # (bounding growth for import-only consumers like
        # scripts/sha2_20_driver.py); THIS process's dir is pruned
        # later, in main() after the precompile/AOT warm phase, when
        # the entries the run needs carry fresh timestamps — the old
        # import-time prune of the current dir could evict them
        _prune_bench_caches(_root, exclude=os.path.basename(cache))
        jax.config.update("jax_compilation_cache_dir", cache)
        # cache EVERYTHING: behind the tunnel even a "cheap" compile is a
        # multi-second RPC, and a fresh process re-pays it for every graph
        # below the threshold (the 2^16 prove traces ~500 distinct graphs;
        # at the default 1.0s threshold ~400 of them recompiled every run)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass


_enable_compile_cache()


def _start_ledger():
    """Process-wide compile ledger + per-graph compile logging. Runs after
    the cache dir is pinned (importing boojum_tpu configures jax)."""
    try:
        import jax

        if os.environ.get("BENCH_LOG_COMPILES", "").strip() != "0":
            jax.config.update("jax_log_compiles", True)
        from boojum_tpu.utils.profiling import start_compile_ledger

        return start_compile_ledger()
    except Exception as e:
        _log(f"compile ledger unavailable: {e!r}")
        return None


_LEDGER = _start_ledger()

# ---------------------------------------------------------------------------
# Watchdog: the driver kills the bench (rc=124, no JSON parsed) if it runs
# past its timeout. A compile RPC stuck on the tunnel blocks the main thread
# inside C++ where Python signals never fire, so a daemon THREAD prints the
# best-known result and hard-exits while the main thread is still blocked.
# ---------------------------------------------------------------------------

_STATE = {
    "metric": None,
    "unit": "s",
    "phase": "import",
    "reps": [],           # completed timed rep walls (service mode:
                          # the single proofs/sec figure)
    "service": None,      # --service: the service drain summary
    "warm_wall": None,    # warm-up (first, compile-laden) prove wall
    "stages": {},         # per-stage split of the reported rep (the warm-up
                          # split until the first timed rep lands, so EVERY
                          # line — including the watchdog's — carries one)
    "peak_mem": {},       # device/host memory high water, updated per prove
    "ntt_eps": None,
    "done": False,
}
_EMIT_LOCK = threading.Lock()

# bench JSON line schema version. 2: stage split and peak_mem promoted to
# every line (previously only present when the stage sink happened to be
# installed), schema field added.
_LINE_SCHEMA = 2

# the LIVE stage sink of the prove currently in flight: the watchdog reads
# it when _STATE["stages"] has no completed-prove split yet, so a line
# fired MID-prove (the stuck-compile case schema 2 exists to diagnose)
# still shows which stages finished before the stall
_LIVE_SINK = {"sink": None}

# the LIVE span recorder of the prove in flight (the PR 2 flight
# recorder's time axis): a watchdog line fired mid-phase carries the
# PARTIAL hierarchical span tree — open spans annotated "unclosed" with
# their elapsed wall — instead of an empty stage split, so a timeout
# localizes to the exact sub-stage that stalled (BENCH_r04 gave
# `"stages": {}` and no localization at all). _prove_recorded installs a
# recorder for EVERY prove, with or without BOOJUM_TPU_REPORT. "bench"
# holds the BENCH-LIFETIME recorder main() installs before the first
# phase: a watchdog line fired OUTSIDE a prove (precompile / AOT load /
# setup — exactly where BENCH_r03/r04 burned their budgets) falls back
# to it, so those phases' spans (precompile_compile_pool, aot_load,
# aot_warm, setup stages) localize the stall too.
_LIVE_REC = {"rec": None, "bench": None, "flight": None}


def _set_phase(name):
    """One phase transition: the bench JSON line's `phase` field and the
    blackbox heartbeat stream (utils/blackbox.py) must never disagree
    about where the budget went."""
    _STATE["phase"] = name
    try:
        from boojum_tpu.utils import blackbox as _bb

        _bb.set_phase(name)
    except Exception:
        pass


def _phase_deadline(name, env, default_s):
    """A blackbox deadline alarm for one phase ("setup may take 300 s, a
    rep may take 60 s") — expiry produces a LOCALIZED stack dump instead
    of a silent global watchdog line. A no-op nullcontext when no
    blackbox is armed or the env var disables it (=0)."""
    import contextlib

    try:
        from boojum_tpu.utils import blackbox as _bb

        bb = _bb.current_blackbox()
        if bb is None:
            return contextlib.nullcontext()
        budget = float(os.environ.get(env, "") or default_s)
        if budget <= 0:
            return contextlib.nullcontext()
        return bb.deadline(name, budget)
    except Exception:
        return contextlib.nullcontext()


def _partial_span_tree():
    rec = _LIVE_REC["rec"] or _LIVE_REC["bench"]
    if rec is None:
        return None
    try:
        tree = rec.tree()
        return tree or None
    except Exception:
        return None


def _update_peak_mem():
    """Fold current device/host memory high-water marks into _STATE
    (best-effort: XLA:CPU exposes no device stats; ru_maxrss always
    works on linux)."""
    pm = dict(_STATE["peak_mem"])
    try:
        from boojum_tpu.utils import metrics as _metrics

        dm = _metrics.device_memory_stats()
        if dm:
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                if k in dm:
                    pm[f"device_{k}"] = max(pm.get(f"device_{k}", 0), dm[k])
        census = _metrics.live_buffer_census()
        if census is not None:
            pm["live_buffer_bytes"] = max(
                pm.get("live_buffer_bytes", 0), census[1]
            )
    except Exception:
        pass
    try:
        import resource

        pm["host_max_rss_kb"] = resource.getrusage(
            resource.RUSAGE_SELF
        ).ru_maxrss
    except Exception:
        pass
    with _EMIT_LOCK:
        if not _STATE["done"]:
            _STATE["peak_mem"] = pm


def _prove_recorded(label, fn):
    """Run one prove; with BOOJUM_TPU_REPORT set, record it as a labeled
    ProveReport JSONL line (span tree + metrics + digest checkpoints +
    compile-ledger summary — utils/report.py). WITHOUT the env var a bare
    SpanRecorder still runs so a watchdog line fired mid-prove can carry
    the partial span tree (nothing is written anywhere in that mode)."""
    path = os.environ.get("BOOJUM_TPU_REPORT")
    if not path:
        from boojum_tpu.utils import spans as _spans

        rec = _spans.SpanRecorder(sync=False)
        _LIVE_REC["rec"] = rec
        prev = _spans.install_recorder(rec)
        try:
            out = fn()
            # success: drop the ref so a later stall OUTSIDE a prove never
            # reports this finished tree as "the prove in flight" (a prove
            # that RAISED keeps it — its partial tree is the diagnosis)
            _LIVE_REC["rec"] = None
        finally:
            _spans.install_recorder(prev)
            _update_peak_mem()
        return out
    from boojum_tpu.utils import report as _report

    with _report.flight_recording(label=label) as rec:
        _LIVE_REC["rec"] = rec.spans
        # the watchdog flushes THIS recorder's partial line if the prove
        # is still in flight when the budget dies (os._exit skips the
        # finally below — exactly how r03/r04 lost their artifacts)
        _LIVE_REC["flight"] = rec
        try:
            out = fn()
            _LIVE_REC["rec"] = None
        finally:
            # a failed prove still leaves its (partial, error-annotated)
            # report line — that is the diagnosable-timeout posture the
            # watchdog/ledger already follow
            _update_peak_mem()
            try:
                _report.append_jsonl(path, _report.build_report(rec))
                _log(f"ProveReport line ({label}) appended to {path}")
                _LIVE_REC["flight"] = None
            except Exception as e:  # recorder must never sink the bench
                _log(f"ProveReport write failed: {e!r}")
    return out


def _live_stage_split():
    """Snapshot the in-flight prove's completed stages (empty when no
    prove has started)."""
    sink = _LIVE_SINK["sink"]
    if not sink:
        return {}
    return {name: round(dt, 3) for name, dt in list(sink)}


def _vs_baseline(value):
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    try:
        base = json.load(open(base_path))
        if base.get("metric") == _STATE["metric"] and base.get("value"):
            return round(base["value"] / value, 3)
    except Exception:
        pass
    return 1.0


def _emit(status):
    """Print the one JSON line (exactly once) and return it."""
    with _EMIT_LOCK:
        if _STATE["done"]:
            return
        _STATE["done"] = True
        reps = sorted(_STATE["reps"])
        if reps:
            value = reps[len(reps) // 2]
        elif _STATE["warm_wall"] is not None:
            # no clean rep, but the protocol DID complete once (compile
            # time included) — report that wall, flagged
            value = _STATE["warm_wall"]
            status = status + "+warm_only"
        else:
            # nothing completed: report elapsed as a lower bound
            value = round(time.perf_counter() - _T0, 1)
            status = status + "+no_prove"
        out = {
            "metric": _STATE["metric"] or "sha256_8192B_prove_wall",
            "value": round(value, 4),
            "unit": _STATE["unit"],
            "vs_baseline": _vs_baseline(value),
            "schema": _LINE_SCHEMA,
            "status": status,
            "phase": _STATE["phase"],
            "reps": [round(r, 4) for r in _STATE["reps"]],
            "stages": _STATE["stages"] or _live_stage_split(),
            "peak_mem": _STATE["peak_mem"],
        }
        # which field backend ran (ISSUE 20): a babybear line moves half
        # the bytes of the same goldilocks geometry, so --trend /--slo
        # must split series by field straight from the line
        try:
            from boojum_tpu.field.spec import active_field

            out["field"] = active_field()
        except Exception:
            pass
        if _STATE["service"] is not None:
            out["service"] = _STATE["service"]
        if status != "ok":
            # a watchdog/failure line localizes the stall: the partial
            # hierarchical span tree of the prove in flight (open spans
            # carry error="unclosed" + elapsed wall), not just the flat
            # stage split
            tree = _partial_span_tree()
            if tree is not None:
                out["span_tree"] = tree
        if _STATE["ntt_eps"] is not None:
            out["ntt_goldilocks_elems_per_s"] = _STATE["ntt_eps"]
        # which on-device representation ran (ISSUE 10): BENCH_r05+ can
        # attribute any wall-clock delta to the limb-resident pipeline
        # (or its absence) straight from the line
        try:
            from boojum_tpu.prover.pallas_sweep import (
                limb_resident_enabled,
                limb_sweep_enabled,
            )

            out["limb_resident"] = bool(limb_resident_enabled())
            out["limb_sweep"] = bool(limb_sweep_enabled())
        except Exception:
            pass
        # machine/software identity (ISSUE 12): the same block the AOT
        # manifest validates on, so --trend groups this line with the
        # right machine's history
        try:
            from boojum_tpu.prover.aot import platform_info

            out["host"] = platform_info()
        except Exception:
            pass
        # the roofline cost record of the last completed prove (ISSUE
        # 12): per-stage achieved GFLOP/s & GB/s vs peak — the "which
        # kernel left perf on the table" axis BENCH_r05+ lines carry
        # (the kernel list is the analytic sheet's coverage; it rides
        # the report artifact, not this line)
        try:
            from boojum_tpu.utils import costmodel as _costmodel

            rec_cost = _costmodel.last_cost_record()
            if rec_cost:
                out["cost"] = {
                    k: v for k, v in rec_cost.items()
                    if k not in ("kernels", "attributed_kernels")
                }
        except Exception:
            pass
        # live-telemetry time series (queue-less in bench, but device
        # memory + live-buffer census over the whole run): the same
        # `telemetry` record the service's report lines carry, so a
        # watchdog line shows WHEN memory climbed, not just the peak
        try:
            from boojum_tpu.utils import telemetry as _telemetry

            sampler = _telemetry.current_sampler()
            if sampler is not None:
                out["telemetry"] = sampler.snapshot()
        except Exception:
            pass
        # the compile-ledger summary rides on EVERY line (including the
        # watchdog's) so a timeout is diagnosable from the JSON alone:
        # which graph compiled longest, how much the cache saved, whether
        # the process was still paying compile when the budget ran out
        if _LEDGER is not None:
            try:
                out["compile_ledger"] = _LEDGER.summary()
                ledger_path = os.environ.get(
                    "BENCH_LEDGER_JSON",
                    os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "compile_ledger.json",
                    ),
                )
                _LEDGER.dump_json(ledger_path)
                out["compile_ledger"]["artifact"] = ledger_path
            except Exception:
                pass
        print(json.dumps(out), flush=True)


def _flush_report_artifact():
    """ISSUE 15 satellite: make the BOOJUM_TPU_REPORT artifact durable
    BEFORE the timeout JSON line prints. The r03/r04 rounds left NO
    partial JSONL because the in-flight prove's report line is appended
    in a finally that os._exit never reaches — so the watchdog appends
    that partial line itself, then fsyncs the artifact."""
    path = os.environ.get("BOOJUM_TPU_REPORT")
    if not path:
        return
    flight = _LIVE_REC.get("flight")
    if flight is not None:
        try:
            from boojum_tpu.utils import report as _report

            _report.append_jsonl(path, _report.build_report(flight))
            _log(f"partial ProveReport line flushed to {path}")
        except Exception as e:
            _log(f"partial ProveReport flush failed: {e!r}")
    try:
        with open(path, "a") as f:
            f.flush()
            os.fsync(f.fileno())
    except Exception:
        pass


def _watchdog(budget_s):
    deadline = _T0 + budget_s
    while True:
        now = time.perf_counter()
        if _STATE["done"]:
            return
        if now >= deadline:
            _log(f"watchdog fired in phase {_STATE['phase']!r}")
            # forensics BEFORE the JSON line: an armed blackbox dumps
            # all-thread stacks + span tree into the sidecar/artifact,
            # and the report artifact is flushed+fsynced — the timeout
            # line is the LAST thing this process says, never the only
            try:
                from boojum_tpu.utils import blackbox as _bb

                bb = _bb.current_blackbox()
                if bb is not None:
                    bb.dump("watchdog", budget_s=budget_s)
            except Exception:
                pass
            _flush_report_artifact()
            _emit("timeout")
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(0)
        time.sleep(min(5.0, deadline - now))


def build_sha256(num_bytes: int):
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry, LookupParameters
    from boojum_tpu.gadgets import allocate_u8_input, sha256

    geom = CSGeometry(
        num_columns_under_copy_permutation=60,
        num_witness_columns=0,
        num_constant_columns=8,
        max_allowed_constraint_degree=7,
    )
    # capacity scales with the message: 8 kB fills a 2^16 trace, the
    # north-star 128 kB fills 2^20 (reference sha256/mod.rs:269 scaling)
    capacity = 1 << max(17, (num_bytes // 8192).bit_length() + 16)
    cs = ConstraintSystem(
        geom, capacity,
        lookup_params=LookupParameters(width=4, num_repetitions=8),
    )
    data = bytes(i % 255 for i in range(num_bytes))
    sha256(cs, allocate_u8_input(cs, data))
    return cs


def build_fma(log_n: int):
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate

    # degree-3 chunks keep every relation at degree <= 4, so the whole
    # pipeline runs at LDE factor 4 (half the memory of the SHA geometry)
    geom = CSGeometry(
        num_columns_under_copy_permutation=16,
        num_witness_columns=0,
        num_constant_columns=6,
        max_allowed_constraint_degree=3,
    )
    cs = ConstraintSystem(geom, 1 << log_n)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    steps = ((1 << log_n) - 8) * per_row
    for _ in range(steps):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    return cs


def _measure_ntt():
    """NTT throughput (BASELINE.md tracked metric): Goldilocks elems/s for a
    batched forward+inverse pair at bench scale, warm."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from boojum_tpu.ntt import (
            fft_natural_to_bitreversed,
            ifft_bitreversed_to_natural,
        )

        cols, log_n = 64, 16
        rng = np.random.default_rng(0)
        from boojum_tpu.field import gl

        a = jnp.asarray(
            rng.integers(0, gl.P, size=(cols, 1 << log_n), dtype=np.uint64)
        )
        ntt_reps = 8

        # chain the reps ON DEVICE (one dispatch): behind the network
        # tunnel every executable launch costs a ~10 ms round trip, which
        # would otherwise measure the tunnel, not the chip
        @jax.jit
        def _ntt_chain(x):
            def body(_, v):
                return ifft_bitreversed_to_natural(
                    fft_natural_to_bitreversed(v)
                )

            return jax.lax.fori_loop(0, ntt_reps, body, x)

        jax.block_until_ready(_ntt_chain(a))  # compile
        t1 = time.perf_counter()
        jax.block_until_ready(_ntt_chain(a))
        dt = time.perf_counter() - t1
        _STATE["ntt_eps"] = int(2 * ntt_reps * cols * (1 << log_n) / dt)
    except Exception as e:
        _log(f"ntt side metric failed: {e!r}")


def _is_transient(exc) -> bool:
    s = repr(exc).lower()
    return any(k in s for k in
               ("response body", "connection", "unavailable", "deadline",
                "internal", "tunnel", "socket", "reset"))


def main():
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    threading.Thread(target=_watchdog, args=(budget,), daemon=True).start()
    # black-box recorder (ISSUE 15): with BOOJUM_TPU_BLACKBOX /
    # BOOJUM_TPU_STALL_S armed, a heartbeat thread stamps a crash-safe
    # sidecar (phase, open span, compile deltas, rss) and stall /
    # deadline / SIGTERM dumps land in the report artifact — the layer
    # that turns the next rc=124 into a stack trace
    try:
        from boojum_tpu.utils import blackbox as _bb

        _bb.ensure_started(label="bench")
    except Exception as e:
        _log(f"blackbox failed to start: {e!r}")

    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
    from boojum_tpu.utils.profiling import collect_stages, stop_collecting_stages
    from boojum_tpu.utils import spans as _spans

    # bench-lifetime span recorder: the per-prove recorders of
    # _prove_recorded install OVER it (and restore it after), so a
    # watchdog line fired in ANY phase — precompile, AOT load, setup —
    # carries a span tree instead of "stages": {}
    bench_rec = _spans.SpanRecorder(sync=False)
    _LIVE_REC["bench"] = bench_rec
    _spans.install_recorder(bench_rec)

    # bench-lifetime telemetry sampler (BOOJUM_TPU_TELEMETRY_INTERVAL
    # cadence, =0 is rejected by the parser — there is no off switch
    # because a 1 Hz census costs microseconds): every ProveReport line
    # and the final bench JSON line carry its time series
    try:
        from boojum_tpu.utils import telemetry as _telemetry

        sampler = _telemetry.TelemetrySampler()
        _telemetry.install_sampler(sampler)
        sampler.start()
    except Exception as e:
        _log(f"telemetry sampler failed to start: {e!r}")

    circuit = os.environ.get("BENCH_CIRCUIT", "sha256")
    reps = int(os.environ.get("BENCH_REPS", "3"))
    lde = int(
        os.environ.get("BENCH_LDE", "8" if circuit == "sha256" else "4")
    )
    config = ProofConfig(
        fri_lde_factor=lde,
        merkle_tree_cap_size=16,
        num_queries=int(os.environ.get("BENCH_QUERIES", "50")),
        pow_bits=0,
        fri_final_degree=16,
    )
    _set_phase("synthesis")
    if circuit == "sha256":
        num_bytes = int(os.environ.get("BENCH_SHA_BYTES", "8192"))
        cs = build_sha256(num_bytes)
        _STATE["metric"] = f"sha256_{num_bytes}B_prove_wall"
    else:
        log_n = int(os.environ.get("BENCH_LOG_N", "10"))
        cs = build_fma(log_n)
        _STATE["metric"] = f"fma_2^{log_n}_prove_wall"

    asm = cs.into_assembly()
    print(f"trace_len={asm.trace_len}", file=sys.stderr, flush=True)
    if asm.trace_len >= (1 << 19):
        # at the 2^20 HBM ceiling, queueing all Q coset sweeps async lets
        # neighbors' working sets overlap and OOM (round-3 finding) — the
        # overlapped prover no longer barriers by default, so the bench
        # opts in for big traces (export BOOJUM_TPU_SYNC_SWEEPS=0 to
        # experiment without it)
        os.environ.setdefault("BOOJUM_TPU_SYNC_SWEEPS", "1")
        _log("large trace: defaulting BOOJUM_TPU_SYNC_SWEEPS=1")

    if "--build-artifacts" in sys.argv:
        # AOT build step: compile the whole dispatch surface (kernel
        # library + setup + one full prove) into a deployment bundle
        # under BOOJUM_TPU_AOT_DIR (default ./aot_artifacts), emit the
        # ledger line and exit — after this, a cold process proves with
        # zero XLA compiles (see BASELINE.md "AOT artifact protocol")
        _set_phase("build_artifacts")
        from boojum_tpu.prover import aot as _aot

        out_root = _aot.aot_dir() or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "aot_artifacts"
        )
        workers = int(os.environ.get("BENCH_PRECOMPILE_WORKERS", "8"))
        _log(f"building AOT artifact bundle under {out_root}")
        manifest = _aot.build_bundle(
            asm, config, out_root, ledger=_LEDGER, max_workers=workers
        )
        _log(
            f"bundle {manifest['dir']}: {manifest['num_kernels']} kernels"
            f" ({manifest['num_exports']} exported), "
            f"{manifest['cache_bytes'] / 2**20:.1f} MiB cache"
        )
        _prune_bench_caches(os.path.dirname(os.path.abspath(__file__)))
        _emit("build_artifacts")
        return

    precompile_only = "--precompile-only" in sys.argv
    no_precompile = (
        "--no-precompile" in sys.argv
        or os.environ.get("BENCH_PRECOMPILE", "").strip() == "0"
    )
    aot_warmed = False
    if os.environ.get("BOOJUM_TPU_AOT_DIR", "").strip():
        # artifact store first: a bundle hit replaces the precompile
        # sweep outright — the warm phase becomes O(deserialization) and
        # each kernel's ledger entry carries aot_hit, so the warm-up
        # wall on this run's JSON line is attributed to deserialization
        # rather than compilation
        _set_phase("aot_load")
        from boojum_tpu.prover import aot as _aot

        try:
            stats = _aot.load_and_warm(
                _aot.aot_dir(), asm, config, ledger=_LEDGER
            )
        except _aot.AotBundleError:
            # BOOJUM_TPU_AOT_REQUIRE: a missing/stale bundle is a hard
            # failure, not a silent fall-through to the compile bill
            raise
        except Exception as e:  # noqa: BLE001 — an unexpected loader
            # bug must degrade to the precompile sweep, not kill the run
            _log(f"aot load failed (continuing to precompile): {e!r}")
            stats = None
        if stats is not None and not stats.get("aborted"):
            aot_warmed = True
            _log(f"aot warm done: {json.dumps(stats)}")
        else:
            _log("no usable AOT bundle; falling back to precompile sweep")
    if (precompile_only or not no_precompile) and not aot_warmed:
        # overlap the remote compile round-trips BEFORE the first dispatch
        # pays them serially; everything lands in the persistent cache
        _set_phase("precompile")
        workers = int(os.environ.get("BENCH_PRECOMPILE_WORKERS", "8"))
        _log(f"parallel precompile of the kernel library ({workers} workers)")
        try:
            from boojum_tpu.prover.precompile import precompile

            led = precompile(
                asm, config, max_workers=workers, ledger=_LEDGER
            )
            _log(
                "precompile done: "
                f"{json.dumps(led.summary())}"
            )
        except Exception as e:
            if precompile_only:
                raise
            _log(f"precompile failed (continuing to prove): {e!r}")
    # prune AFTER the warm phase: entries this run just read/wrote (and
    # any artifact-bundle installs) carry fresh timestamps and survive;
    # an import-time prune could evict the current bucket's entries
    _prune_bench_caches(os.path.dirname(os.path.abspath(__file__)))
    if precompile_only:
        _emit("precompile_only")
        return

    _set_phase("setup")
    _log("generating setup (compiles on a cold cache)")
    with _phase_deadline("setup", "BENCH_SETUP_DEADLINE_S", 300.0):
        setup = generate_setup(asm, config)

    # warm-up (compiles) then timed runs; report the MEDIAN rep and its
    # per-stage wall-clock split (the tunnel-attached device is noisy, so a
    # single rep is not a number of record). The stage sink runs from the
    # warm-up on, so every emitted line — including a watchdog line fired
    # mid-warm-up — carries a stage split (schema 2).
    _set_phase("warmup_prove")
    _log("warm-up prove (compiles on a cold cache)")
    for attempt in (1, 2):
        sink = collect_stages()
        _LIVE_SINK["sink"] = sink
        t0 = time.perf_counter()  # per-attempt: a failed attempt's stall
        # must not inflate the reported warm wall
        try:
            with _phase_deadline(
                "warmup_prove", "BENCH_WARMUP_DEADLINE_S", 600.0
            ):
                proof = _prove_recorded(
                    "warmup", lambda: prove(asm, setup, config)
                )
            break
        except Exception as e:
            # the tunnel occasionally drops a big compile RPC; one retry
            # re-enters with everything already cached up to the drop
            if attempt == 1 and _is_transient(e):
                _log(f"warm-up prove failed transiently, retrying: {e!r}")
                continue
            raise
    _STATE["warm_wall"] = round(time.perf_counter() - t0, 4)
    with _EMIT_LOCK:
        if not _STATE["done"]:
            _STATE["stages"] = {name: round(dt, 3) for name, dt in sink}
    _log(f"warm-up prove done in {_STATE['warm_wall']}s; verifying")
    _set_phase("verify")
    assert verify(setup.vk, proof, asm.gates)

    if "--service" in sys.argv:
        # throughput mode: drain BENCH_SERVICE_REQS requests through the
        # proving service (shape-bucketed queue, device-resident caches,
        # scheduler-picked placement) and report proofs/sec — the number
        # BENCH rounds need once single-proof wall stops being the
        # bottleneck. The warm-up prove above already validated parity
        # and warmed the caches the service will hit.
        _set_phase("service_drain")
        from boojum_tpu.service import ProvingService, ServiceConfig

        scfg = ServiceConfig.from_env()
        if not os.environ.get("BOOJUM_TPU_SERVICE_PRECOMPILE", "").strip():
            # the bench's own precompile sweep already filled the cache
            # for the variant a meshless/proof-parallel drain dispatches
            scfg.precompile = "off"
        svc = ProvingService(scfg)
        nreq = int(os.environ.get("BENCH_SERVICE_REQS", "4"))
        _log(
            f"service drain: {nreq} requests, "
            f"mesh={None if svc.mesh is None else dict(svc.mesh.shape)}"
        )
        if os.environ.get("BENCH_SERVICE_GATEWAY", "").strip() in (
            "1", "true", "on", "yes"
        ):
            # ISSUE 11: admit over the real loopback HTTP front door so
            # the measured proofs/sec includes the network admission
            # plane (auth, quota check, DRR queue) — two equal-weight
            # tenants split the request stream
            import urllib.request

            from boojum_tpu.service import (
                Gateway, GatewayConfig, TenantSpec,
            )

            gw = Gateway(
                svc,
                GatewayConfig(tenants=[
                    TenantSpec(id="bench-a", token="bench-a"),
                    TenantSpec(id="bench-b", token="bench-b"),
                ]),
                resolver=lambda spec: (asm, setup, config),
            )
            port = gw.start()
            _log(f"service drain: gateway admission on :{port}")
            drain_t0 = time.perf_counter()
            jobs = []
            for i in range(nreq):
                r = urllib.request.Request(
                    gw.url("/prove"),
                    data=json.dumps({
                        "priority": (
                            "interactive" if i == nreq - 1 else "batch"
                        ),
                    }).encode(),
                    headers={
                        "Authorization":
                            f"Bearer bench-{'ab'[i % 2]}",
                        "Content-Type": "application/json",
                    },
                    method="POST",
                )
                with urllib.request.urlopen(r, timeout=30) as resp:
                    jobs.append(json.loads(resp.read())["job"])
            # worker drains in the gateway's background thread
            requests = gw.wait_jobs(jobs, timeout_s=3600)
            drain_wall = time.perf_counter() - drain_t0
            gw.stop()
            summary = svc.summary(wall_s=drain_wall)
            summary["gateway_admitted"] = len(jobs)
        else:
            requests = [
                svc.submit(
                    asm, setup, config,
                    priority="interactive" if i == nreq - 1 else "batch",
                )
                for i in range(nreq)
            ]
            summary = svc.run_worker()
        assert summary["failed"] == 0, summary
        for r in requests:
            r.result(timeout=1.0)
        pps = summary.get("proofs_per_sec") or 0.0
        _log(f"service drain done: {json.dumps(summary)}")
        with _EMIT_LOCK:
            if not _STATE["done"]:
                base = (_STATE["metric"] or "prove_wall").replace(
                    "_prove_wall", ""
                )
                _STATE["metric"] = f"{base}_service_proofs_per_sec"
                _STATE["unit"] = "proofs/s"
                _STATE["reps"] = [pps]
                _STATE["service"] = summary
        stop_collecting_stages()
        if not os.environ.get("BENCH_SKIP_NTT"):
            _set_phase("ntt_metric")
            _measure_ntt()
        _emit("ok")
        return

    _set_phase("timed_reps")
    rep_stages = []
    for i in range(reps):
        sink = collect_stages()
        _LIVE_SINK["sink"] = sink
        t0 = time.perf_counter()
        with _phase_deadline(f"rep{i + 1}", "BENCH_REP_DEADLINE_S", 60.0):
            proof = _prove_recorded(
                f"rep{i + 1}", lambda: prove(asm, setup, config)
            )
        rep_wall = time.perf_counter() - t0
        rep_stages.append({name: round(dt, 3) for name, dt in sink})
        # update reps + the matching median split atomically wrt the
        # watchdog's _emit (same lock), so the reported stage split always
        # belongs to the rep whose wall is the reported median
        with _EMIT_LOCK:
            _STATE["reps"].append(rep_wall)
            order = sorted(range(len(_STATE["reps"])),
                           key=lambda j: _STATE["reps"][j])
            _STATE["stages"] = rep_stages[order[len(order) // 2]]
        _log(f"rep {i + 1}/{reps}: {rep_wall:.3f}s")
    stop_collecting_stages()

    if not os.environ.get("BENCH_SKIP_NTT"):
        _set_phase("ntt_metric")
        _measure_ntt()
    _emit("ok")


if __name__ == "__main__":
    main()
